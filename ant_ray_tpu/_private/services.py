"""Cluster process bootstrap (ref: python/ray/_private/services.py +
node.py — start/stop of gcs_server, raylet, workers)."""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
import uuid

from ant_ray_tpu._private.protocol import ClientPool, find_free_port

logger = logging.getLogger(__name__)

_READY_TIMEOUT_S = 30.0


_AXON_TRIGGER = "PALLAS_AXON_POOL_IPS"
_AXON_STASH = "ART_AXON_POOL_IPS_STASH"


def control_plane_env() -> dict:
    """Environment for control-plane daemons (GCS / node daemon /
    dashboard), with the site-level TPU plugin registration DEFERRED.

    On this image, ``sitecustomize`` imports all of jax at interpreter
    start whenever ``PALLAS_AXON_POOL_IPS`` is set (~1.7s per process on
    one core) — pure overhead for daemons that never run accelerator
    code.  The trigger is stashed, not dropped: spawners of jax-needing
    children (worker pool, job drivers) call :func:`accelerator_env` to
    restore it."""
    env = os.environ.copy()
    trigger = env.pop(_AXON_TRIGGER, None)
    if trigger is not None:
        env[_AXON_STASH] = trigger
    return env


def accelerator_env(env: dict) -> dict:
    """Restore the stashed TPU-plugin trigger for a child that runs
    accelerator code — unless the tree is pinned to the CPU backend
    (tests), where the registration would be dead weight."""
    stashed = env.get(_AXON_STASH)
    if stashed is not None and env.get("ART_JAX_PLATFORM", "") != "cpu":
        env[_AXON_TRIGGER] = stashed
    return env


def _wait_ready(proc: subprocess.Popen, marker: str) -> str:
    """Read the child's stdout until `<marker> <address>` appears."""
    deadline = time.monotonic() + _READY_TIMEOUT_S
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"process exited (code={proc.poll()}) before ready")
        text = line.decode(errors="replace").strip()
        if text.startswith(marker):
            return text.split(" ", 1)[1]
    raise RuntimeError(f"timed out waiting for {marker}")


def start_gcs(session_dir: str,
              port: int | None = None,
              ha_replica_id: str | None = None
              ) -> tuple[subprocess.Popen, str]:
    """Start (or restart — same port + store file) the GCS head.

    Tables persist to ``<session_dir>/gcs_store.db`` so a restarted head
    resumes the cluster (ref: Redis-backed GCS fault tolerance,
    src/ray/gcs/store_client/redis_store_client.h).  With
    ``ha_replica_id`` the process joins the replicated control plane
    over that same store: the lease elects a leader, the rest run as
    warm standbys (follower reads + NotLeader redirects)."""
    port = port or find_free_port()
    store = os.path.join(session_dir, "gcs_store.db")
    cmd = [sys.executable, "-m", "ant_ray_tpu._private.gcs",
           "--port", str(port), "--store", store,
           "--export-dir", os.path.join(session_dir, "export_events"),
           "--monitor-pid", str(os.getpid())]
    if ha_replica_id:
        cmd += ["--ha-replica-id", ha_replica_id]
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE, stderr=_log_file(session_dir, "gcs.err"),
        env=control_plane_env(), start_new_session=True)
    address = _wait_ready(proc, "GCS_READY")
    return proc, address


def start_node(gcs_address: str, resources: dict, session_dir: str,
               labels: dict | None = None) -> tuple[subprocess.Popen, str]:
    proc = subprocess.Popen(
        [sys.executable, "-m", "ant_ray_tpu._private.node_daemon",
         "--gcs-address", gcs_address,
         "--resources", json.dumps(resources),
         "--session-dir", session_dir,
         "--labels", json.dumps(labels or {}),
         "--monitor-pid", str(os.getpid())],
        stdout=subprocess.PIPE, stderr=_log_file(session_dir, "noded.err"),
        env=control_plane_env(), start_new_session=True)
    address = _wait_ready(proc, "NODED_READY")
    return proc, address


def _log_file(session_dir: str, name: str):
    log_dir = os.path.join(session_dir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    return open(os.path.join(log_dir, name), "ab")


def default_resources(num_cpus: int | None, num_tpus: int | None,
                      resources: dict | None) -> dict:
    out = dict(resources or {})
    out["CPU"] = float(num_cpus if num_cpus is not None
                       else (os.cpu_count() or 1))
    if num_tpus is not None:
        out["TPU"] = float(num_tpus)
    else:
        from ant_ray_tpu._private.accelerators import tpu  # noqa: PLC0415

        detected = tpu.num_tpu_chips()
        if detected:
            out["TPU"] = float(detected)
    return out


def new_session_dir() -> str:
    session_dir = os.path.join(
        "/tmp", f"art_session_{uuid.uuid4().hex[:10]}")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    return session_dir


def start_dashboard(gcs_address: str, session_dir: str
                    ) -> tuple[subprocess.Popen, str]:
    proc = subprocess.Popen(
        [sys.executable, "-m", "ant_ray_tpu._private.dashboard",
         "--gcs-address", gcs_address,
         "--session-dir", session_dir,
         "--monitor-pid", str(os.getpid())],
        stdout=subprocess.PIPE, stderr=_log_file(session_dir, "dash.err"),
        env=control_plane_env(), start_new_session=True)
    url = _wait_ready(proc, "DASH_READY")
    return proc, url


def start_cluster(num_cpus: int | None = None, num_tpus: int | None = None,
                  resources: dict | None = None,
                  include_dashboard: bool | None = None) -> dict:
    """Start head (GCS) + one node daemon (+ dashboard); returns
    addresses + procs."""
    from ant_ray_tpu._private.config import global_config  # noqa: PLC0415

    session_dir = new_session_dir()
    gcs_proc, gcs_address = start_gcs(session_dir)
    procs = [gcs_proc]
    try:
        node_proc, node_address = start_node(
            gcs_address, default_resources(num_cpus, num_tpus, resources),
            session_dir)
        procs.insert(0, node_proc)
        dashboard_url = ""
        want_dashboard = (include_dashboard if include_dashboard is not None
                          else global_config().include_dashboard)
        if want_dashboard:
            try:
                import aiohttp  # noqa: F401, PLC0415
            except ImportError:
                logger.warning("aiohttp not installed; dashboard (state "
                               "API, /metrics, job server) disabled")
                want_dashboard = False
        if want_dashboard:
            try:
                dash_proc, dashboard_url = start_dashboard(
                    gcs_address, session_dir)
            except Exception as e:  # noqa: BLE001 — dashboard is optional
                logger.warning("dashboard failed to start: %s", e)
            else:
                procs.insert(0, dash_proc)
                # Publish for late-joining drivers / the jobs SDK.
                pool = ClientPool()
                try:
                    pool.get(gcs_address).call("KVPut", {
                        "key": "dashboard_url",
                        "value": dashboard_url.encode()}, retries=3)
                finally:
                    pool.close_all()
    except Exception:
        stop_processes(procs)
        raise
    store_dir = _store_dir_of(node_address)
    return {
        "gcs_address": gcs_address,
        "node_address": node_address,
        "store_dir": store_dir,
        "session_dir": session_dir,
        "dashboard_url": dashboard_url,
        "processes": procs,
    }


def _store_dir_of(node_address: str) -> str:
    pool = ClientPool()
    try:
        info = pool.get(node_address).call("GetNodeInfo", retries=3)
        return info.object_store_dir
    finally:
        pool.close_all()


def find_local_node(gcs_address: str) -> tuple[str, str]:
    """Pick a node for a connecting driver (first alive node)."""
    pool = ClientPool()
    try:
        nodes = pool.get(gcs_address).call("GetAllNodes", retries=5)
        for info in nodes.values():
            if info.alive:
                return info.address, info.object_store_dir
        raise RuntimeError("no alive nodes in cluster")
    finally:
        pool.close_all()


def stop_processes(procs: list) -> None:
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()
    deadline = time.monotonic() + 15
    for proc in procs:
        remaining = max(0.1, deadline - time.monotonic())
        try:
            proc.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            proc.kill()
