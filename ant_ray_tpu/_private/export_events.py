"""Export-event pipeline: durable JSONL lifecycle events for external
consumers (ref: the reference's RayEventRecorder + export_*.proto event
schemas written for off-cluster pipelines — actor / node / job /
placement-group / task definition and lifecycle events).

Events append to one file per source type under the session's export
dir (``event_EXPORT_ACTOR.log`` etc.), newest-last, with a single
size-based rotation (``.1`` backup) so a chatty cluster can't fill the
disk.  The format is self-describing JSON — no proto toolchain needed
to consume it.

Writes happen on a dedicated writer thread (the recorder is called from
the GCS event loop — per-event file I/O there would stall heartbeats
and lease RPCs); ``record()`` only enqueues.  ``read()`` drains the
queue first so readers see their own writes.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time

SOURCE_TYPES = ("EXPORT_ACTOR", "EXPORT_NODE", "EXPORT_JOB",
                "EXPORT_PLACEMENT_GROUP", "EXPORT_TASK",
                "EXPORT_DRIVER_JOB", "EXPORT_WORKER")


def _to_jsonable(value):
    """IDs and bytes → hex/str so events stay plain JSON."""
    if isinstance(value, dict):
        return {str(_to_jsonable(k)): _to_jsonable(v)
                for k, v in value.items()}
    if isinstance(value, (list, tuple, set)):
        return [_to_jsonable(v) for v in value]
    if isinstance(value, (bytes, bytearray)):
        return value.hex()
    if hasattr(value, "hex") and not isinstance(value, (int, float)):
        try:
            return value.hex()
        except Exception:  # noqa: BLE001
            return str(value)
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


class ExportEventRecorder:
    """Append-only JSONL event writer with per-source rotation and an
    off-loop writer thread."""

    def __init__(self, export_dir: str,
                 max_file_bytes: int = 16 * 1024 * 1024):
        self._dir = export_dir
        self._max = max_file_bytes
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue(maxsize=100000)
        self._files: dict[str, object] = {}   # source -> open handle
        self._sizes: dict[str, int] = {}
        os.makedirs(export_dir, exist_ok=True)
        self._writer = threading.Thread(target=self._drain, daemon=True,
                                        name="export-events-writer")
        self._writer.start()

    def _path(self, source_type: str) -> str:
        return os.path.join(self._dir, f"event_{source_type}.log")

    def record(self, source_type: str, event_type: str,
               entity_id, data: dict | None = None) -> None:
        """Enqueue one event; never raises and never touches the disk
        on the caller's thread (export is observability, not control
        flow)."""
        try:
            with self._seq_lock:
                self._seq += 1
                seq = self._seq
            event = {"seq": seq,
                     "timestamp": time.time(),
                     "source_type": source_type,
                     "event_type": event_type,
                     "entity_id": _to_jsonable(entity_id),
                     "data": _to_jsonable(data or {})}
            self._queue.put_nowait(event)
        except Exception:  # noqa: BLE001 — full queue drops, never breaks
            pass

    def _drain(self) -> None:
        while True:
            event = self._queue.get()
            try:
                self._write(event)
            except Exception:  # noqa: BLE001 — disk full etc.
                pass
            finally:
                self._queue.task_done()

    def _handle(self, source_type: str):
        f = self._files.get(source_type)
        if f is None:
            path = self._path(source_type)
            f = open(path, "a")
            self._files[source_type] = f
            try:
                self._sizes[source_type] = os.path.getsize(path)
            except OSError:
                self._sizes[source_type] = 0
        return f

    def _write(self, event: dict) -> None:
        source = event["source_type"]
        line = json.dumps(event, separators=(",", ":")) + "\n"
        if self._sizes.get(source, 0) + len(line) > self._max:
            f = self._files.pop(source, None)
            if f is not None:
                f.close()
            path = self._path(source)
            try:
                os.replace(path, path + ".1")
            except OSError:
                pass
            self._sizes[source] = 0
        f = self._handle(source)
        f.write(line)
        f.flush()
        self._sizes[source] = self._sizes.get(source, 0) + len(line)

    def flush(self, timeout: float = 5.0) -> None:
        """Block until every enqueued event hit the disk (bounded)."""
        deadline = time.monotonic() + timeout
        while not self._queue.empty() or self._queue.unfinished_tasks:
            if time.monotonic() > deadline:
                return
            time.sleep(0.01)

    def read(self, source_type: str | None = None,
             limit: int = 1000) -> list[dict]:
        """Newest-last events, optionally filtered by source type (the
        dashboard's /api/export_events and tests read through this).
        Call off the event loop — this parses files."""
        self.flush()
        sources = [source_type] if source_type else list(SOURCE_TYPES)
        out: list[dict] = []
        for src in sources:
            path = self._path(src)
            for candidate in (path + ".1", path):
                try:
                    with open(candidate) as f:
                        for line in f:
                            try:
                                out.append(json.loads(line))
                            except ValueError:
                                continue
                except OSError:
                    continue
        # Order by wall time first: seq restarts at 1 when a head
        # restarts into the same (append-mode) files, so seq alone
        # would rank the previous run's events as newest forever.
        out.sort(key=lambda e: (e.get("timestamp", 0), e.get("seq", 0)))
        return out[-limit:]
