"""Hot-frame codec: the zero-pickle wire format for the PushTask path.

The pickled tuple frames in protocol.py are a fine general transport,
but at 10k+ actor calls/s the per-call cost is dominated by framing,
not compute: every ``pickle.dumps(TaskSpec)`` re-encodes ~15 invariant
fields (function name, owner address, retry policy, ...) and copies
``args_payload`` through the pickle buffer, and every reply is its own
pickled frame.  The reference system pays for its direct actor-call
plane with compact protobuf frames (ref: PushTaskRequest,
src/ray/protobuf/core_worker.proto) — this module is that idea for the
pickle transport:

* **templates** — the invariant ``TaskSpec`` fields per (actor, method)
  / (function, options) are interned ONCE per connection into a small
  header-template cache (:class:`TemplateCache` sender-side, a plain
  ``dict`` receiver-side) and referenced by a u32 id afterwards;
* **calls** — each call ships only the varying fields (task-id,
  sequence number, attempt, optional trace context) as a fixed struct
  pack, with ``args_payload`` riding as the raw frame tail — the bytes
  never round-trip through pickle;
* **acks** — replies are fixed-layout records that BATCH: one hot-ack
  frame carries every reply that completed in the same io-loop tick
  (see RpcServer's coalesced ack flush).

Negotiation is additive within ``protocol.PROTOCOL_VERSION``: the
client's HELLO advertises ``hot=HOT_WIRE_VERSION``; a server that
understands it replies a HELLO-ack and only then does the client emit
hot frames.  An old peer on either side never advertises / never acks,
so traffic transparently stays on the pickled path — no flag-day (the
mixed-version interop tests in tests/test_hot_wire.py pin this).

Evolution policy (enforced by artlint's frame-schema drift checker
against the committed ``_lint/wire_frames.json`` snapshot): frame-kind
values and flag bits are FROZEN, and the two field tables below are
append-only — renaming, removing, or reordering an entry breaks peers
that negotiated the same hot version, so it fails lint loudly.

Pickle appears here only in the blessed helpers (template bodies, the
rare sampled trace context, exception acks) — never on the per-call
byte path; artlint's ``pickle-in-hot-path`` rule keeps it that way.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

from ant_ray_tpu._private.ids import TaskID
from ant_ray_tpu._private.specs import TaskSpec

#: Hot-wire feature version advertised in the HELLO handshake.  Bump on
#: any non-additive change to the layouts below; peers negotiate
#: ``min(theirs, ours)`` and a version-0 peer simply stays pickled.
HOT_WIRE_VERSION = 1

# Hot-frame body kinds (first byte of a _HOT_FLAG frame body).  Values
# are wire contract — frozen by the frame-schema snapshot.
HOT_TEMPLATE = 1          # u32 template id + pickled invariant fields
HOT_CALL = 2              # one PushTask: varying fields + raw payload
HOT_ACKS = 3              # 1..N concatenated reply records

#: Invariant TaskSpec fields carried by a template, in wire order
#: (append-only; the artlint snapshot pins order AND membership).
TEMPLATE_FIELDS = (
    "function_id", "function_name", "num_returns", "owner_address",
    "resources", "max_retries", "retry_exceptions", "actor_id",
    "method_name", "concurrency_group",
)

#: Varying fields each HOT_CALL carries, in wire order (append-only).
CALL_FIELDS = ("task_id", "sequence_no", "attempt", "trace_ctx",
               "args_payload")

# Struct layouts for the fixed parts of a call / ack record.
_CALL_HEAD = struct.Struct("!QIB")      # msg_id, template_id, id_len
_CALL_VARY = struct.Struct("!qIB")      # sequence_no, attempt, flags
_ACK_HEAD = struct.Struct("!QB")        # msg_id, status
_U16 = struct.Struct("!H")
_U32 = struct.Struct("!I")
_U64 = struct.Struct("!Q")

_FLAG_TRACE = 1

# Reply return-kind codes (wire contract, frozen like the frame kinds).
_RET_INLINE, _RET_PLASMA, _RET_ERROR, _RET_STREAM_END = 0, 1, 2, 3
_RET_CODES = {"inline": _RET_INLINE, "plasma": _RET_PLASMA,
              "error": _RET_ERROR, "stream_end": _RET_STREAM_END}
_RET_NAMES = {v: k for k, v in _RET_CODES.items()}

_ACK_OK, _ACK_EXC = 0, 1

#: Live codec counters (GIL-atomic int bumps): cheap observability for
#: tests and the node transfer-stats surface — proving a path really
#: ran hot beats inferring it from throughput.
counters = {"templates_encoded": 0, "calls_encoded": 0,
            "calls_decoded": 0, "acks_encoded": 0, "acks_decoded": 0,
            "fallback_ineligible": 0, "fallback_cache_full": 0}


class HotFrameError(Exception):
    """A hot frame could not be decoded (truncated body, unknown or
    oversized template id, bad kind byte).  Carries ``msg_id`` when the
    header parsed far enough to know which call to fail — the server
    then acks that call with the error instead of dropping it."""

    def __init__(self, message: str, msg_id: int | None = None):
        super().__init__(message)
        self.msg_id = msg_id


# ------------------------------------------------------------- templates

def template_key(spec: TaskSpec) -> tuple | None:
    """Hashable interning key over the invariant fields, or None when
    the spec is not hot-eligible.  Eligibility is deliberately the
    plain-call shape (no placement group, runtime env, label selector,
    or scheduling strategy): those specs are rare, cold, and carry
    arbitrary nested dicts — they stay on the pickled path."""
    if (spec.placement_group_id is not None or spec.runtime_env
            or spec.label_selector or spec.scheduling_strategy
            or not isinstance(spec.args_payload, (bytes, bytearray,
                                                  memoryview))):
        return None
    try:
        return (spec.function_id, spec.function_name, spec.num_returns,
                spec.owner_address,
                tuple(sorted(spec.resources.items())),
                spec.max_retries, spec.retry_exceptions, spec.actor_id,
                spec.method_name, spec.concurrency_group)
    except TypeError:       # unhashable oddball (custom resources etc.)
        return None


class TemplateCache:
    """Sender-side template interner, one per CONNECTION — the ids are
    meaningless to any other peer, so the owner (RpcClient) discards
    the cache whenever the connection turns over and re-interns against
    the fresh one (the receiver's table died with the old socket)."""

    # Bound: past this the sender stops interning NEW templates (calls
    # fall back to pickled frames) instead of growing without limit or
    # evicting ids the receiver still remembers.
    MAX_TEMPLATES = 1024

    __slots__ = ("_ids",)

    def __init__(self):
        self._ids: dict[tuple, int] = {}

    def intern(self, key: tuple) -> tuple[int | None, bool]:
        """-> (template_id | None when full, is_new)."""
        tid = self._ids.get(key)
        if tid is not None:
            return tid, False
        if len(self._ids) >= self.MAX_TEMPLATES:
            return None, False
        tid = len(self._ids)
        self._ids[key] = tid
        return tid, True


def encode_template(tid: int, spec: TaskSpec) -> bytes:
    """HOT_TEMPLATE body: the invariant fields travel pickled — a
    template is sent once per (connection, call shape), so its encoding
    cost is irrelevant and pickle handles the dict-valued fields."""
    fields = pickle.dumps(
        (spec.function_id, spec.function_name, spec.num_returns,
         spec.owner_address, spec.resources, spec.max_retries,
         spec.retry_exceptions, spec.actor_id, spec.method_name,
         spec.concurrency_group), protocol=5)
    counters["templates_encoded"] += 1
    return b"%c%s%s" % (HOT_TEMPLATE, _U32.pack(tid), fields)


def decode_template(body) -> tuple[int, tuple]:
    """-> (template_id, invariant-field tuple) from a HOT_TEMPLATE body
    (kind byte included)."""
    try:
        tid, = _U32.unpack_from(body, 1)
        fields = pickle.loads(bytes(body[5:]))
    except (struct.error, pickle.UnpicklingError, EOFError,
            ValueError) as e:
        raise HotFrameError(f"bad template frame: {e!r}") from e
    if not isinstance(fields, tuple) or len(fields) < len(TEMPLATE_FIELDS):
        raise HotFrameError("template field tuple malformed")
    return tid, fields


# ------------------------------------------------------------------ calls

def encode_call(tid: int, spec: TaskSpec, msg_id: int) -> bytes:
    """HOT_CALL body: fixed struct head + varying fields, with
    ``args_payload`` as the raw tail (never pickled, single copy into
    the frame join)."""
    task_id = spec.task_id._bytes
    trace = spec.trace_ctx
    if trace is not None:
        tbytes = pickle.dumps(trace, protocol=5)
        vary = _CALL_VARY.pack(spec.sequence_no, spec.attempt,
                               _FLAG_TRACE) + _U16.pack(len(tbytes)) \
            + tbytes
    else:
        vary = _CALL_VARY.pack(spec.sequence_no, spec.attempt, 0)
    counters["calls_encoded"] += 1
    return b"%c%s%s%s%s" % (
        HOT_CALL, _CALL_HEAD.pack(msg_id, tid, len(task_id)), task_id,
        vary, spec.args_payload)


def decode_call(body, templates: dict) -> tuple[int, TaskSpec]:
    """-> (msg_id, TaskSpec) from a HOT_CALL body (kind byte included),
    resolving the template against the receiver's per-connection table.
    Raises :class:`HotFrameError` (with msg_id when parseable) on a
    truncated body or a template id the table does not know — a
    reconnected peer re-sends templates, so an unknown id means a
    protocol bug or a forged frame, never a wait-and-see."""
    try:
        msg_id, tid, id_len = _CALL_HEAD.unpack_from(body, 1)
    except struct.error as e:
        raise HotFrameError(f"truncated call head: {e!r}") from e
    tmpl = templates.get(tid)
    if tmpl is None:
        raise HotFrameError(
            f"unknown hot template id {tid} (have "
            f"{len(templates)}) — stale or oversized template ref",
            msg_id=msg_id)
    off = 1 + _CALL_HEAD.size
    try:
        task_id = bytes(body[off:off + id_len])
        if len(task_id) != id_len:
            raise HotFrameError("truncated task id", msg_id=msg_id)
        off += id_len
        sequence_no, attempt, flags = _CALL_VARY.unpack_from(body, off)
        off += _CALL_VARY.size
        trace_ctx = None
        if flags & _FLAG_TRACE:
            tlen, = _U16.unpack_from(body, off)
            off += _U16.size
            trace_ctx = pickle.loads(bytes(body[off:off + tlen]))
            off += tlen
    except (struct.error, pickle.UnpicklingError, EOFError,
            ValueError) as e:
        raise HotFrameError(f"truncated call body: {e!r}",
                            msg_id=msg_id) from e
    counters["calls_decoded"] += 1
    # bytes(), not a view: the spec outlives the read buffer (executor
    # queue) and must survive a pickled re-push on the retry path.
    payload = bytes(body[off:])
    return msg_id, TaskSpec(
        task_id=TaskID(task_id),
        function_id=tmpl[0], function_name=tmpl[1],
        args_payload=payload, num_returns=tmpl[2],
        owner_address=tmpl[3], resources=dict(tmpl[4]),
        max_retries=tmpl[5], retry_exceptions=tmpl[6],
        actor_id=tmpl[7], method_name=tmpl[8],
        sequence_no=sequence_no, concurrency_group=tmpl[9],
        trace_ctx=trace_ctx, attempt=attempt)


# ------------------------------------------------------------------- acks

def _pack_blob(out: list, data) -> bool:
    if isinstance(data, (bytes, bytearray, memoryview)):
        out.append(_U32.pack(len(data)))
        out.append(bytes(data) if not isinstance(data, bytes) else data)
        return True
    return False


def encode_ack(msg_id: int, reply: Any) -> bytes | None:
    """One reply record for the batched ack frame, or None when the
    reply is not the known PushTask shape (the caller then falls back
    to a pickled reply frame for just that call — mixing is fine, the
    client resolves futures by msg_id either way)."""
    returns = reply.get("returns") if isinstance(reply, dict) else None
    if not isinstance(returns, list) or len(reply) != 1 \
            or len(returns) > 0xFFFF:
        return None
    out = [_ACK_HEAD.pack(msg_id, _ACK_OK), _U16.pack(len(returns))]
    for entry in returns:
        kind, data = entry
        code = _RET_CODES.get(kind)
        if code is None:
            return None
        out.append(b"%c" % code)
        if code in (_RET_INLINE, _RET_ERROR):
            if not _pack_blob(out, data):
                return None
        elif code == _RET_PLASMA:
            if not isinstance(data, int) or data < 0:
                return None
            out.append(_U64.pack(data))
        else:                                    # stream_end
            count, err_payload = data
            out.append(_U32.pack(count))
            if err_payload is None:
                out.append(b"\x00")
            else:
                out.append(b"\x01")
                if not _pack_blob(out, err_payload):
                    return None
    counters["acks_encoded"] += 1
    return b"".join(out)


def encode_ack_exc(msg_id: int, exc: BaseException) -> bytes:
    """Exception reply record (handler raised instead of returning)."""
    try:
        blob = pickle.dumps(exc, protocol=5)
    except Exception:  # noqa: BLE001 — unpicklable error payload
        from ant_ray_tpu._private.protocol import RpcError  # noqa: PLC0415

        blob = pickle.dumps(RpcError(repr(exc)), protocol=5)
    counters["acks_encoded"] += 1
    return _ACK_HEAD.pack(msg_id, _ACK_EXC) + _U32.pack(len(blob)) + blob


def frame_acks(records: list[bytes]) -> bytes:
    """HOT_ACKS body: the coalesced flush — one frame, N acks."""
    return b"%c%s" % (HOT_ACKS, b"".join(records))


def decode_acks(body) -> list[tuple[int, Any, bool]]:
    """-> [(msg_id, reply-or-exception, is_exception)] from a HOT_ACKS
    body (kind byte included).  Raises HotFrameError on truncation —
    an undecodable ack frame is a dead connection, not a skippable
    record (later records' boundaries are unknown)."""
    out: list[tuple[int, Any, bool]] = []
    view = memoryview(body) if not isinstance(body, memoryview) else body
    off = 1
    end = len(view)
    try:
        while off < end:
            msg_id, status = _ACK_HEAD.unpack_from(view, off)
            off += _ACK_HEAD.size
            if status == _ACK_EXC:
                blen, = _U32.unpack_from(view, off)
                off += _U32.size
                exc = pickle.loads(bytes(view[off:off + blen]))
                off += blen
                out.append((msg_id, exc, True))
                continue
            n_returns, = _U16.unpack_from(view, off)
            off += _U16.size
            returns = []
            for _ in range(n_returns):
                code = view[off]
                off += 1
                if code in (_RET_INLINE, _RET_ERROR):
                    blen, = _U32.unpack_from(view, off)
                    off += _U32.size
                    data: Any = bytes(view[off:off + blen])
                    if len(data) != blen:
                        raise HotFrameError("truncated ack blob")
                    off += blen
                elif code == _RET_PLASMA:
                    data, = _U64.unpack_from(view, off)
                    off += _U64.size
                elif code == _RET_STREAM_END:
                    count, = _U32.unpack_from(view, off)
                    off += _U32.size
                    has_err = view[off]
                    off += 1
                    err_payload = None
                    if has_err:
                        blen, = _U32.unpack_from(view, off)
                        off += _U32.size
                        err_payload = bytes(view[off:off + blen])
                        off += blen
                    data = (count, err_payload)
                else:
                    raise HotFrameError(f"bad return kind code {code}")
                returns.append((_RET_NAMES[code], data))
            out.append((msg_id, {"returns": returns}, False))
    except (struct.error, IndexError, pickle.UnpicklingError,
            EOFError) as e:
        raise HotFrameError(f"truncated ack frame: {e!r}") from e
    counters["acks_decoded"] += len(out)
    return out
