"""cgroup v2 resource isolation for node processes (ref:
src/ray/common/cgroup2/ — CgroupManagerInterface and its factory: the
raylet splits SYSTEM processes (daemons) from USER processes (workers)
into sibling cgroups so a worker memory blow-up is contained by the
kernel before it takes the daemon down).

Layout under the delegated root (usually ``/sys/fs/cgroup``):

    <root>/art_<session>/            (+memory +cpu enabled)
        system/                      node daemon + helpers
        workers/                     every spawned worker
            memory.max               workers' collective hard cap
            memory.oom.group = 0     kill one worker, not the group
            cpu.weight               relative share vs system

Opt-in via ``enable_cgroups`` (needs a writable delegated cgroup2 tree
— root or a systemd-delegated slice).  Everything degrades to a no-op
when unavailable: isolation is an upgrade, never a boot requirement.
The constructor takes the tree root so tests drive it against a fake
directory."""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

DEFAULT_ROOT = "/sys/fs/cgroup"


class CgroupManager:
    """Best-effort cgroup v2 subtree for one node's processes."""

    def __init__(self, session_name: str, root: str = DEFAULT_ROOT,
                 workers_memory_max: int = 0,
                 workers_cpu_weight: int = 0):
        self._root = root
        self._base = os.path.join(root, f"art_{session_name}")
        self._system = os.path.join(self._base, "system")
        self._workers = os.path.join(self._base, "workers")
        self._workers_memory_max = workers_memory_max
        self._workers_cpu_weight = workers_cpu_weight
        self.active = False

    # ------------------------------------------------------------ setup

    @staticmethod
    def available(root: str = DEFAULT_ROOT) -> bool:
        """A usable cgroup2 tree: the controllers file exists and the
        root is writable (delegation)."""
        return (os.path.isfile(os.path.join(root, "cgroup.controllers"))
                and os.access(root, os.W_OK))

    def setup(self) -> bool:
        """Create the subtree and apply limits; False (and inactive) on
        any failure — callers must treat isolation as optional."""
        try:
            os.makedirs(self._system, exist_ok=True)
            os.makedirs(self._workers, exist_ok=True)
            # Enable controllers for the children.  Requires the base's
            # parent to have them enabled for us (delegation); partial
            # support (e.g. cpu missing) is tolerated per-controller.
            avail = self._read(os.path.join(self._base,
                                            "cgroup.controllers")) or ""
            enable = [c for c in ("memory", "cpu") if c in avail.split()]
            if enable:
                self._write(os.path.join(self._base,
                                         "cgroup.subtree_control"),
                            " ".join(f"+{c}" for c in enable))
            # Per-controller best effort: a host that delegates only
            # memory still gets memory isolation — a failed cpu.weight
            # write must not throw away the memory.max already applied.
            if self._workers_memory_max > 0:
                self._try_limit(os.path.join(self._workers, "memory.max"),
                                str(self._workers_memory_max))
                # One runaway worker dies alone — group-kill would turn
                # a single OOM into a whole-node worker massacre.
                self._try_limit(os.path.join(self._workers,
                                             "memory.oom.group"), "0")
            if self._workers_cpu_weight > 0:
                self._try_limit(os.path.join(self._workers, "cpu.weight"),
                                str(self._workers_cpu_weight))
            self.active = True
            return True
        except OSError as e:
            logger.info("cgroup2 isolation unavailable: %s", e)
            self.active = False
            self.cleanup()    # never leak a half-built subtree
            return False

    @classmethod
    def _try_limit(cls, path: str, value: str) -> None:
        try:
            cls._write(path, value)
        except OSError as e:
            logger.info("cgroup limit %s not applied: %s", path, e)

    # ----------------------------------------------------------- placing

    def add_system_process(self, pid: int) -> bool:
        return self._add(self._system, pid)

    def add_worker_process(self, pid: int) -> bool:
        return self._add(self._workers, pid)

    def _add(self, cgroup: str, pid: int) -> bool:
        if not self.active:
            return False
        try:
            self._write_procs(os.path.join(cgroup, "cgroup.procs"), pid)
            return True
        except OSError:
            return False  # process already gone, or no permission

    # ---------------------------------------------------------- teardown

    def workers_memory_current(self) -> int | None:
        value = self._read(os.path.join(self._workers, "memory.current"))
        try:
            return int(value) if value is not None else None
        except ValueError:
            return None

    def cleanup(self) -> None:
        """Migrate stragglers back to the root and remove the subtree.
        Safe to call when inactive or half-built."""
        if not os.path.isdir(self._base):
            return
        for group in (self._workers, self._system):
            procs = self._read(os.path.join(group, "cgroup.procs")) or ""
            for pid in procs.split():
                try:
                    self._write_procs(
                        os.path.join(self._root, "cgroup.procs"), int(pid))
                except (OSError, ValueError):
                    pass
            try:
                os.rmdir(group)
            except OSError:
                pass
        try:
            os.rmdir(self._base)
        except OSError:
            pass
        self.active = False

    # ------------------------------------------------------------- io

    @staticmethod
    def _read(path: str) -> str | None:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            return None

    @staticmethod
    def _write(path: str, value: str) -> None:
        with open(path, "w") as f:
            f.write(value)

    @staticmethod
    def _write_procs(path: str, pid: int) -> None:
        # cgroup.procs takes one pid per write() call; append mode is
        # equivalent on cgroupfs and keeps a faithful record when the
        # manager is driven against a plain-directory fake in tests.
        with open(path, "a") as f:
            f.write(f"{pid}\n")
