"""User-defined metrics (ref: python/ray/util/metrics.py Counter/Gauge/
Histogram) recorded to the GCS metrics table and exported as Prometheus
text by the dashboard's /metrics endpoint."""

from __future__ import annotations

import time


def _record(payload: dict):
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if not global_worker.connected:
        return  # metrics are best-effort outside a cluster
    runtime = global_worker.runtime
    gcs = getattr(runtime, "_gcs", None)
    if gcs is None:
        return  # local mode
    runtime._send_oneway(runtime.gcs_address, "MetricRecord", payload)


class _Metric:
    _type = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def _emit(self, value: float, tags: dict | None,
              extra: dict | None = None):
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        payload = {"name": self._name, "type": self._type,
                   "value": float(value), "tags": merged,
                   "description": self._description}
        if extra:
            payload.update(extra)
        _record(payload)


class Counter(_Metric):
    _type = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("counters only increase")
        self._emit(value, tags)


class Gauge(_Metric):
    _type = "gauge"

    def set(self, value: float, tags: dict | None = None):
        self._emit(value, tags)


class Histogram(_Metric):
    _type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: list | None = None, tag_keys: tuple = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(float(b) for b in (boundaries or []))

    def observe(self, value: float, tags: dict | None = None,
                exemplar: dict | None = None):
        # Boundaries ride along so the GCS can tally per-bucket counts
        # and /metrics can render real _bucket{le=...} lines.  An
        # exemplar ({"trace_id": ...}) links the observation to a
        # concrete trace, OpenMetrics style: the GCS keeps the latest
        # per series and /metrics renders `# {trace_id="..."} v ts`.
        extra: dict = {"boundaries": self._boundaries}
        if exemplar:
            extra["exemplar"] = {"labels": dict(exemplar),
                                 "value": float(value),
                                 "ts": time.time()}
        self._emit(value, tags, extra=extra)
