"""ActorPool: balance tasks across a fixed set of actors
(ref: python/ray/util/actor_pool.py)."""

from __future__ import annotations

from collections import deque


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


class _Slot:
    """One submitted task: queued until an actor frees, then in flight."""

    __slots__ = ("fn", "value", "ref", "actor")

    def __init__(self, fn, value):
        self.fn = fn
        self.value = value
        self.ref = None
        self.actor = None


class ActorPool:
    def __init__(self, actors):
        self._idle = list(actors)
        self._slots: deque[_Slot] = deque()   # submission order

    # ---- internals

    def _start_queued(self):
        for slot in self._slots:
            if not self._idle:
                break
            if slot.ref is None:
                slot.actor = self._idle.pop(0)
                slot.ref = slot.fn(slot.actor, slot.value)

    def _inflight(self):
        return [s for s in self._slots if s.ref is not None]

    def _free(self, slot: _Slot):
        self._idle.append(slot.actor)
        slot.actor = None
        self._start_queued()

    def _wait_one(self, timeout):
        """Block until some in-flight task finishes; free its actor."""
        art = _art()
        inflight = self._inflight()
        if not inflight:
            raise RuntimeError("pool wedged: queued work, no actors")
        done, _ = art.wait([s.ref for s in inflight], num_returns=1,
                           timeout=timeout)
        if not done:
            raise TimeoutError("no task finished within timeout")
        return done[0]

    # ---- public (ref surface)

    def submit(self, fn, value):
        """fn(actor, value) -> ObjectRef; starts when an actor is free."""
        self._slots.append(_Slot(fn, value))
        self._start_queued()

    def has_next(self) -> bool:
        return bool(self._slots)

    def has_free(self) -> bool:
        return bool(self._idle)

    def get_next(self, timeout: float | None = None):
        """Next result in submission order."""
        if not self._slots:
            raise StopIteration("no pending results")
        art = _art()
        head = self._slots[0]
        while head.ref is None:
            self._wait_one(timeout)  # frees an actor eventually…
            # …but only collection frees it in our accounting, so reap:
            self._start_queued()
            if head.ref is None:
                # head still queued: collect some finished slot's actor
                for slot in list(self._slots):
                    if slot.ref is not None and slot is not head:
                        ready, _ = art.wait([slot.ref], num_returns=1,
                                            timeout=0)
                        if ready:
                            # leave its value for its own get_next; just
                            # recycle the actor
                            if slot.actor is not None:
                                self._free(slot)
                            break
        self._slots.popleft()
        value = art.get(head.ref, timeout=timeout)
        if head.actor is not None:
            self._free(head)
        return value

    def get_next_unordered(self, timeout: float | None = None):
        """Next completed result, any order."""
        if not self._slots:
            raise StopIteration("no pending results")
        art = _art()
        self._start_queued()
        ref = self._wait_one(timeout)
        for slot in self._slots:
            if slot.ref is ref:
                self._slots.remove(slot)
                value = art.get(ref, timeout=timeout)
                if slot.actor is not None:
                    self._free(slot)
                return value
        raise AssertionError("completed ref not in pool")

    def map(self, fn, values):
        """Ordered map over the pool (generator of results)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
