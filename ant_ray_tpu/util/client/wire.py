"""Client↔server value encoding — ONE definition for both ends.

Values cross the proxy as the object plane's own serialized payloads
(pickle-5 + out-of-band buffers), so the wire format changes in exactly
one place.
"""

from __future__ import annotations

from typing import Any

from ant_ray_tpu._private import serialization


def pack(value: Any) -> bytes:
    return serialization.serialize(value).to_payload()


def unpack(payload) -> Any:
    return serialization.deserialize(
        serialization.SerializedObject.from_payload(payload))
