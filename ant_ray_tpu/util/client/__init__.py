"""Remote client proxy — connect to a cluster from outside it.

The analogue of Ray Client (ref: python/ray/util/client/): a driver-side
proxy server runs next to the cluster, and remote processes connect with
``art.init("art://host:port")``.  The client process runs no daemons and
holds no object store; every API call is proxied over the RPC substrate
to the server, which executes it against a real in-cluster driver
runtime and pins results until the client releases them.
"""

from ant_ray_tpu.util.client.runtime import ClientRuntime
from ant_ray_tpu.util.client.server import ClientServer, start_client_server

__all__ = ["ClientRuntime", "ClientServer", "start_client_server"]
