"""Client-side runtime: the ``art://`` proxy connection.

A ``CoreRuntime`` implementation (the same interface local mode and the
in-cluster ``ClusterRuntime`` implement) whose every method is one RPC to
a :class:`~ant_ray_tpu.util.client.server.ClientServer`.  The client
process runs no daemons: ObjectRefs here are mirrors of server-side refs,
released back to the server when garbage collected
(ref: python/ray/util/client/worker.py — the Ray Client data plane).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Sequence

from ant_ray_tpu import exceptions
from ant_ray_tpu._private import serialization
from ant_ray_tpu._private.protocol import RpcClient
from ant_ray_tpu._private.worker import CoreRuntime
from ant_ray_tpu.actor import ActorHandle
from ant_ray_tpu.object_ref import ObjectRef, ObjectRefGenerator, set_refcount_hook
from ant_ray_tpu.util.client.wire import pack as _pack
from ant_ray_tpu.util.client.wire import unpack as _unpack


class ClientRuntime(CoreRuntime):
    """Proxy runtime behind ``art.init("art://host:port")``."""

    def __init__(self, address: str):
        self._rpc = RpcClient(address)
        self._lock = threading.Lock()
        self._registered: set[str] = set()       # fids/cids known server-side
        self._counts: dict[Any, int] = {}        # oid -> live local mirrors
        self._shutdown = False
        hello = self._rpc.call("ClientHello", {}, retries=3)
        self.protocol_version = hello["version"]
        set_refcount_hook(self._refcount_event)

    @classmethod
    def connect(cls, address: str) -> "ClientRuntime":
        return cls(address)

    # ------------------------------------------------------- ref mirroring

    def _refcount_event(self, event: str, ref: ObjectRef) -> None:
        if self._shutdown:
            return
        oid = ref.id
        with self._lock:
            if event in ("add", "deserialized"):
                self._counts[oid] = self._counts.get(oid, 0) + 1
                return
            if event != "remove":
                return
            n = self._counts.get(oid, 0) - 1
            if n > 0:
                self._counts[oid] = n
                return
            self._counts.pop(oid, None)
        # Fire-and-forget: __del__ may run on ANY thread — including the
        # io-loop thread itself — so a blocking call here could deadlock.
        import asyncio  # noqa: PLC0415

        try:
            asyncio.run_coroutine_threadsafe(
                self._rpc.oneway_async("ClientRelease", {"oids": [oid]}),
                self._rpc._io.loop)
        except Exception:  # noqa: BLE001 — interpreter teardown / lost link
            pass

    def _mirror(self, wire) -> ObjectRef:
        """Build the local mirror of a server-pinned ref.

        The server already counted one pin for this wire handle, and the
        ObjectRef constructor fires the "add" hook — so pins and mirrors
        stay 1:1 without extra bookkeeping."""
        oid, owner = wire
        return ObjectRef(oid, owner_address=owner)

    def _wire(self, ref: ObjectRef) -> tuple:
        return (ref.id, ref.owner_address)

    def _mirror_result(self, result):
        kind, body = result
        if kind == "ref":
            return self._mirror(body)
        if kind == "refs":
            return [self._mirror(w) for w in body]
        if kind == "stream":
            return ObjectRefGenerator(body, self)
        raise exceptions.ArtError(f"bad submit reply kind {kind!r}")

    # ------------------------------------------------------------ code ship

    def _ensure_function(self, remote_function) -> str:
        fid = getattr(remote_function, "_client_fid", None)
        if fid is None:
            fid = uuid.uuid4().hex
            remote_function._client_fid = fid
        if fid not in self._registered:
            self._rpc.call("ClientRegisterFunction", {
                "fid": fid,
                "code": serialization.dumps_code(remote_function.function),
            })
            self._registered.add(fid)
        return fid

    def _ensure_class(self, actor_class) -> str:
        cid = getattr(actor_class, "_client_cid", None)
        if cid is None:
            cid = uuid.uuid4().hex
            actor_class._client_cid = cid
        if cid not in self._registered:
            self._rpc.call("ClientRegisterClass", {
                "cid": cid,
                "code": serialization.dumps_code(actor_class.cls),
            })
            self._registered.add(cid)
        return cid

    # ------------------------------------------------------------ tasks

    def submit_task(self, remote_function, args, kwargs, options):
        fid = self._ensure_function(remote_function)
        return self._mirror_result(self._rpc.call("ClientSubmitTask", {
            "fid": fid,
            "payload": _pack((list(args), dict(kwargs))),
            "options": options,
        }, timeout=0))

    def create_actor(self, actor_class, args, kwargs, options):
        cid = self._ensure_class(actor_class)
        reduced = self._rpc.call("ClientCreateActor", {
            "cid": cid,
            "payload": _pack((list(args), dict(kwargs))),
            "options": options,
        }, timeout=0)
        return ActorHandle(*reduced)

    def submit_actor_task(self, handle, method_name, args, kwargs, options):
        return self._mirror_result(self._rpc.call("ClientSubmitActorTask", {
            "handle": handle.__reduce__()[1],
            "method": method_name,
            "payload": _pack((list(args), dict(kwargs))),
            "options": options,
        }, timeout=0))

    # ------------------------------------------------------------ objects

    def put(self, value: Any) -> ObjectRef:
        return self._mirror(self._rpc.call(
            "ClientPut", {"payload": _pack(value)}, timeout=0))

    def get(self, refs: Sequence[ObjectRef], timeout: float | None) -> list:
        payloads = self._rpc.call("ClientGet", {
            "refs": [self._wire(r) for r in refs],
            "timeout": timeout,
        }, timeout=0 if timeout is None else timeout + 30)
        return [_unpack(p) for p in payloads]

    def wait(self, refs, num_returns, timeout, fetch_local):
        by_oid = {r.id: r for r in refs}
        ready_ids, not_ready_ids = self._rpc.call("ClientWait", {
            "refs": [self._wire(r) for r in refs],
            "num_returns": num_returns,
            "timeout": timeout,
            "fetch_local": fetch_local,
        }, timeout=0)
        return ([by_oid[i] for i in ready_ids],
                [by_oid[i] for i in not_ready_ids])

    # ------------------------------------------------------------ streaming

    def stream_next(self, task_id, index, timeout):
        wire = self._rpc.call("ClientStreamNext", {
            "task_id": task_id, "index": index, "timeout": timeout,
        }, timeout=0)
        return None if wire is None else self._mirror(wire)

    def release_stream(self, task_id, index):
        # Called from ObjectRefGenerator.__del__ — may run on any thread
        # (including the io loop) and at interpreter teardown, so it must
        # never block.
        import asyncio  # noqa: PLC0415

        try:
            asyncio.run_coroutine_threadsafe(
                self._rpc.oneway_async("ClientStreamRelease",
                                       {"task_id": task_id}),
                self._rpc._io.loop)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------ actors

    def get_actor(self, name: str, namespace: str | None):
        reduced = self._rpc.call("ClientGetActor", {
            "name": name, "namespace": namespace})
        return ActorHandle(*reduced)

    def kill_actor(self, handle, no_restart: bool = True):
        self._rpc.call("ClientKillActor", {
            "handle": handle.__reduce__()[1], "no_restart": no_restart})

    def cancel(self, ref, force=False, recursive=True):
        self._rpc.call("ClientCancel", {
            "ref": self._wire(ref), "force": force, "recursive": recursive})

    # ------------------------------------------------------------ cluster

    def cluster_resources(self) -> dict:
        return self._rpc.call("ClientClusterResources", {})

    def available_resources(self) -> dict:
        return self._rpc.call("ClientAvailableResources", {})

    def nodes(self) -> list[dict]:
        return self._rpc.call("ClientNodes", {})

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        set_refcount_hook(None)
        with self._lock:
            oids = list(self._counts)
            self._counts.clear()
        if oids:
            try:
                self._rpc.call("ClientRelease", {"oids": oids}, timeout=5)
            except Exception:  # noqa: BLE001 — link may already be gone
                pass
        self._rpc.close()
