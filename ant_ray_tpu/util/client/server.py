"""Client proxy server: hosts remote drivers against one in-cluster runtime.

Role-equivalent to the reference's Ray Client server
(ref: python/ray/util/client/server/server.py — a gRPC proxy that owns a
real driver connection and executes API calls on behalf of remote
clients).  Differences driven by this framework's design:

* transport is the shared asyncio RPC substrate (``_private/protocol.py``)
  rather than a dedicated gRPC service — the same frames, retry and chaos
  machinery as every other control-plane hop;
* object values cross the wire as the object plane's own serialized
  payloads (pickle-5 + out-of-band buffers), so numpy/jax arrays keep
  their zero-copy buffer path on the server side;
* the server pins every ObjectRef it hands out (``_refs``) and drops the
  pin when the client's mirror of the ref is garbage collected — the
  client side of the ownership protocol collapses to reference mirroring
  (ref: the client-side reference counting in
  python/ray/util/client/common.py).
"""

from __future__ import annotations

import argparse
import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from ant_ray_tpu._private import serialization
from ant_ray_tpu._private.ids import JobID
from ant_ray_tpu._private.protocol import RpcServer
from ant_ray_tpu.actor import ActorClass, ActorHandle
from ant_ray_tpu.object_ref import ObjectRef
from ant_ray_tpu.remote_function import RemoteFunction
from ant_ray_tpu.util.client.wire import pack as _pack
from ant_ray_tpu.util.client.wire import unpack as _unpack

logger = logging.getLogger(__name__)

PROTOCOL_VERSION = 1


class ClientServer:
    """One proxy server fronting one in-cluster driver runtime."""

    def __init__(self, runtime, host: str = "0.0.0.0", port: int = 0):
        self._runtime = runtime
        self._server = RpcServer(host=host, port=port)
        # Blocking runtime calls (get/wait/submit) must not run on the io
        # loop; a generous pool keeps many concurrently-blocked clients
        # from starving each other.
        self._pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="art-client-srv")
        self._lock = threading.Lock()
        self._functions: dict[str, RemoteFunction] = {}
        self._classes: dict[str, ActorClass] = {}
        # oid -> (ObjectRef, pin count): keeps results alive until every
        # client-side mirror of the ref is released.
        self._refs: dict[Any, list] = {}
        self._streams: dict[Any, Any] = {}  # task_id -> ObjectRefGenerator
        self._server.routes({
            "ClientHello": self._hello,
            "ClientPut": self._put,
            "ClientGet": self._get,
            "ClientWait": self._wait,
            "ClientRegisterFunction": self._register_function,
            "ClientRegisterClass": self._register_class,
            "ClientSubmitTask": self._submit_task,
            "ClientCreateActor": self._create_actor,
            "ClientSubmitActorTask": self._submit_actor_task,
            "ClientGetActor": self._get_actor,
            "ClientKillActor": self._kill_actor,
            "ClientCancel": self._cancel,
            "ClientStreamNext": self._stream_next,
            "ClientStreamRelease": self._stream_release,
            "ClientRelease": self._release,
            "ClientClusterResources": self._cluster_resources,
            "ClientAvailableResources": self._available_resources,
            "ClientNodes": self._nodes,
        })

    # ------------------------------------------------------------ lifecycle

    def start(self) -> str:
        self.address = self._server.start()
        return self.address

    def stop(self) -> None:
        self._server.stop()
        self._pool.shutdown(wait=False)
        with self._lock:
            self._refs.clear()
            self._streams.clear()

    async def _run_blocking(self, fn, *args):
        import asyncio  # noqa: PLC0415

        return await asyncio.get_running_loop().run_in_executor(
            self._pool, fn, *args)

    # ------------------------------------------------------------ ref pins

    def _pin(self, ref: ObjectRef) -> tuple:
        with self._lock:
            entry = self._refs.get(ref.id)
            if entry is None:
                self._refs[ref.id] = [ref, 1]
            else:
                entry[1] += 1
        return (ref.id, ref.owner_address)

    def _pin_result(self, result):
        """Pin a submit result (ref | list[ref]) and return its wire form."""
        if isinstance(result, ObjectRef):
            return ("ref", self._pin(result))
        return ("refs", [self._pin(r) for r in result])

    # ------------------------------------------------------------ handlers

    async def _hello(self, req):
        return {"version": PROTOCOL_VERSION,
                "job_id": self._runtime.job_id}

    async def _put(self, req):
        value = _unpack(req["payload"])
        ref = await self._run_blocking(self._runtime.put, value)
        return self._pin(ref)

    async def _get(self, req):
        refs = [self._resolve_ref(w) for w in req["refs"]]
        values = await self._run_blocking(
            self._runtime.get, refs, req["timeout"])

        def _pack_pinning(v):
            # ObjectRefs nested inside a fetched value become client
            # mirrors on deserialize — pin them here so the server-side
            # borrow outlives this handler's transient value, released
            # by the mirror's eventual ClientRelease.
            ser = serialization.serialize(v)
            for r in ser.contained_refs:
                self._pin(r)
            return ser.to_payload()

        return [_pack_pinning(v) for v in values]

    async def _wait(self, req):
        import time  # noqa: PLC0415

        refs = [self._resolve_ref(w) for w in req["refs"]]
        num_returns = req["num_returns"]
        timeout = req["timeout"]
        # Satisfy the wait server-side (bounded) so the client's poll loop
        # costs one RPC, not one RPC per 5 ms.
        deadline = time.monotonic() + min(
            30.0, timeout if timeout is not None else 30.0)

        def _poll():
            while True:
                ready, not_ready = self._runtime.wait(
                    refs, num_returns, timeout, req["fetch_local"])
                if len(ready) >= num_returns or time.monotonic() >= deadline:
                    return ready, not_ready
                time.sleep(0.005)

        ready, not_ready = await self._run_blocking(_poll)
        return ([r.id for r in ready], [r.id for r in not_ready])

    def _resolve_ref(self, wire) -> ObjectRef:
        oid, owner = wire
        with self._lock:
            entry = self._refs.get(oid)
            if entry is not None:
                return entry[0]
        # A ref minted elsewhere (e.g. nested inside a value the client
        # unpacked) — reconstruct; the borrow was registered when the
        # server deserialized the containing value.
        return ObjectRef(oid, owner_address=owner, _skip_refcount=True)

    async def _register_function(self, req):
        fn = serialization.loads_code(req["code"])
        with self._lock:
            self._functions[req["fid"]] = RemoteFunction(fn)
        return True

    async def _register_class(self, req):
        cls = serialization.loads_code(req["code"])
        with self._lock:
            self._classes[req["cid"]] = ActorClass(cls)
        return True

    async def _submit_task(self, req):
        with self._lock:
            fn = self._functions.get(req["fid"])
        if fn is None:
            raise KeyError(f"unregistered client function {req['fid']!r}")
        args, kwargs = _unpack(req["payload"])
        options = req["options"]
        result = await self._run_blocking(
            lambda: self._runtime.submit_task(fn, args, kwargs, options))
        if options.num_returns == "streaming":
            with self._lock:
                self._streams[result.task_id] = result
            return ("stream", result.task_id)
        return self._pin_result(result)

    async def _create_actor(self, req):
        with self._lock:
            cls = self._classes.get(req["cid"])
        if cls is None:
            raise KeyError(f"unregistered client actor class {req['cid']!r}")
        args, kwargs = _unpack(req["payload"])
        handle = await self._run_blocking(
            lambda: self._runtime.create_actor(
                cls, args, kwargs, req["options"]))
        return handle.__reduce__()[1]

    async def _submit_actor_task(self, req):
        handle = ActorHandle(*req["handle"])
        args, kwargs = _unpack(req["payload"])
        options = req["options"]
        result = await self._run_blocking(
            lambda: self._runtime.submit_actor_task(
                handle, req["method"], args, kwargs, options))
        if options.num_returns == "streaming":
            with self._lock:
                self._streams[result.task_id] = result
            return ("stream", result.task_id)
        return self._pin_result(result)

    async def _get_actor(self, req):
        handle = await self._run_blocking(
            self._runtime.get_actor, req["name"], req["namespace"])
        return handle.__reduce__()[1]

    async def _kill_actor(self, req):
        handle = ActorHandle(*req["handle"])
        await self._run_blocking(
            lambda: self._runtime.kill_actor(handle, req["no_restart"]))
        return True

    async def _cancel(self, req):
        ref = self._resolve_ref(req["ref"])
        await self._run_blocking(
            lambda: self._runtime.cancel(ref, req["force"], req["recursive"]))
        return True

    async def _stream_next(self, req):
        with self._lock:
            gen = self._streams.get(req["task_id"])
        if gen is None:
            return None

        def _next():
            try:
                return gen.next_with_timeout(req["timeout"])
            except StopIteration:
                return None

        ref = await self._run_blocking(_next)
        if ref is None:
            return None
        return self._pin(ref)

    async def _stream_release(self, req):
        with self._lock:
            self._streams.pop(req["task_id"], None)
        return True

    async def _release(self, req):
        with self._lock:
            for oid in req["oids"]:
                entry = self._refs.get(oid)
                if entry is None:
                    continue
                entry[1] -= 1
                if entry[1] <= 0:
                    del self._refs[oid]
        return True

    async def _cluster_resources(self, req):
        return await self._run_blocking(self._runtime.cluster_resources)

    async def _available_resources(self, req):
        return await self._run_blocking(self._runtime.available_resources)

    async def _nodes(self, req):
        return await self._run_blocking(self._runtime.nodes)


def start_client_server(cluster_address: str, host: str = "0.0.0.0",
                        port: int = 0) -> ClientServer:
    """Connect to ``cluster_address`` as a driver and serve remote clients."""
    from ant_ray_tpu._private.config import Config, set_global_config  # noqa: PLC0415
    from ant_ray_tpu._private.core import ClusterRuntime  # noqa: PLC0415

    config = Config().apply_env_overrides()
    set_global_config(config)
    runtime = ClusterRuntime.create(
        address=cluster_address, job_id=JobID.from_random(),
        num_cpus=None, num_tpus=None, resources=None,
        namespace="default", config=config)
    server = ClientServer(runtime, host=host, port=port)
    server.start()
    return server


def main() -> None:
    parser = argparse.ArgumentParser(description="art client proxy server")
    parser.add_argument("--cluster-address", required=True,
                        help="GCS address of the cluster to front")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args()
    server = start_client_server(args.cluster_address, args.host, args.port)
    print(f"ART_CLIENT_SERVER_READY {server.address}", flush=True)
    threading.Event().wait()  # serve forever


if __name__ == "__main__":
    main()
