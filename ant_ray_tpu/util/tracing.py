"""Distributed tracing: task lifecycle events → OpenTelemetry spans
(ref: python/ray/util/tracing/tracing_helper.py — the reference wraps
task/actor calls in OTel spans when ``_tracing_startup_hook`` is set,
and proxy-mocks otel when it isn't installed, :147-176).

Two span sources, ONE code path out: requests traced by the live
tracing plane (observability/tracing_plane.py — contexts minted at
ingresses and PROPAGATED through request metadata, Dapper style)
surface their real cross-process spans via :func:`live_spans`; tasks no
propagated context covered (unsampled traffic) fall back to spans
DERIVED from the buffered task lifecycle events
(submitted/started/finished with parent linkage via contextvar), with
re-executed/retried attempts salted into distinct span ids.  The OTel
SDK stays optional:

* :func:`task_spans` — span objects (trace/span/parent ids, timings);
  propagated spans first, derived spans as the fallback
* :func:`export_otlp_json` — OTLP/JSON file any collector can ingest
* :func:`replay_to_otel` — emit through a real installed
  ``opentelemetry`` TracerProvider when the package is available
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ant_ray_tpu.util.timeline import fetch_task_events

_NS = 1_000_000_000


@dataclass
class Span:
    """One task execution, OTel-shaped."""

    trace_id: str            # 32 hex — the root task of the call tree
    span_id: str             # 16 hex — derived from the task id
    parent_span_id: str      # "" for roots
    name: str
    start_ns: int
    end_ns: int
    ok: bool = True
    attributes: dict = field(default_factory=dict)


def _span_id(task_id: str, attempt: int = 0) -> str:
    # Hash, don't truncate: task ids share a long job-id prefix, so a
    # prefix-slice would collide every span in a job.  The attempt
    # number salts the hash so a re-executed/retried task's span never
    # collides with the original run's (attempt 0 keeps the historical
    # unsalted id).
    import hashlib  # noqa: PLC0415

    key = task_id or ""
    if attempt:
        key = f"{key}#{attempt}"
    return hashlib.blake2b(key.encode(), digest_size=8).hexdigest()


def _trace_id(task_id: str) -> str:
    import hashlib  # noqa: PLC0415

    return hashlib.blake2b((task_id or "").encode(),
                           digest_size=16).hexdigest()


def live_spans(span_events: list[dict] | None = None) -> list[Span]:
    """Propagated request-trace spans (observability/tracing_plane.py:
    minted at ingresses, carried in request meta, published to the GCS
    span ring) as OTel-shaped :class:`Span` objects — real cross-process
    trace/span ids, not post-hoc derivations.  Stage timings surface as
    ``art.stage.<name>_s`` attributes."""
    if span_events is None:
        from ant_ray_tpu.util.timeline import fetch_span_events  # noqa: PLC0415

        span_events = fetch_span_events()
    spans = []
    for s in span_events:
        attrs = dict(s.get("attrs") or {})
        for stage, sec in (s.get("stages") or {}).items():
            attrs[f"art.stage.{stage}_s"] = round(float(sec), 6)
        if s.get("node_id"):
            attrs["art.node_id"] = s["node_id"]
        if s.get("pid"):
            attrs["art.pid"] = s["pid"]
        if s.get("service"):
            attrs["art.service"] = s["service"]
        if s.get("error"):
            attrs["error"] = True
        ts = float(s.get("ts", 0.0))
        spans.append(Span(
            trace_id=s["trace_id"],
            span_id=s["span_id"],
            parent_span_id=s.get("parent_id") or "",
            name=s.get("name", "span"),
            start_ns=int(ts * _NS),
            end_ns=int((ts + float(s.get("dur_s", 0.0))) * _NS),
            ok=not s.get("error"),
            attributes=attrs,
        ))
    return spans


def task_spans(events: list[dict] | None = None,
               span_events: list[dict] | None = None) -> list[Span]:
    """ONE code path for spans: propagated request-trace spans where a
    context travelled (``live_spans``), with driver-local DERIVED spans
    as the fallback for tasks no propagated context covered (unsampled
    traffic, pre-upgrade workers).

    ``trace_id`` groups a call tree: a derived task inherits its root
    ancestor's id, so a driver-submitted task and everything it spawned
    share one trace (the W3C trace-context notion); propagated spans
    carry their minted ingress trace id as-is.  Re-executed/retried
    tasks derive one span per (task_id, attempt), attempt-salted ids."""
    explicit_events = events is not None
    if events is None:
        events = fetch_task_events()
    if span_events is None and not explicit_events:
        # Only reach for the cluster when the caller didn't hand us a
        # specific event set (unit-test / offline usage stays offline).
        from ant_ray_tpu.util.timeline import fetch_span_events  # noqa: PLC0415

        try:
            span_events = fetch_span_events()
        except Exception:  # noqa: BLE001 — no cluster connected
            span_events = []
    live = live_spans(span_events or [])
    # Tasks already covered by a propagated execution span don't get a
    # second derived span (one instrumentation, not two vocabularies).
    covered = {s.attributes["task_id"] for s in live
               if "task_id" in s.attributes}
    by_task: dict[tuple, dict] = {}
    for e in sorted(events, key=lambda e: e["ts"]):
        if e["task_id"] in covered:
            continue
        key = (e["task_id"], int(e.get("attempt") or 0))
        rec = by_task.setdefault(key, {"events": {}})
        rec["events"].setdefault(e["event"], e)

    # task_id -> any attempt's record, for parent-chain resolution (a
    # child's events name only the parent task, not its attempt).
    by_task_any: dict[str, dict] = {}
    for (tid, _attempt), rec in by_task.items():
        by_task_any.setdefault(tid, rec)

    def root_of(task_id: str, hops: int = 0) -> str:
        rec = by_task_any.get(task_id)
        if rec is None or hops > 256:
            return task_id
        for e in rec["events"].values():
            parent = e.get("parent_task_id")
            if parent:
                return root_of(parent, hops + 1)
        return task_id

    spans = list(live)
    for (task_id, attempt), rec in by_task.items():
        ev = rec["events"]
        started = ev.get("started")
        ended = ev.get("finished") or ev.get("failed")
        submitted = ev.get("submitted")
        if started is None:
            continue  # never ran (still queued, or events truncated)
        end_ts = (ended or started)["ts"]
        any_e = started
        parent = None
        for e in ev.values():
            parent = parent or e.get("parent_task_id")
        attributes = {
            "art.task_id": task_id,
            "art.node_id": any_e.get("node_id", ""),
            "art.pid": any_e.get("pid", 0),
        }
        if attempt:
            attributes["art.attempt"] = attempt
        if any_e.get("actor_id"):
            attributes["art.actor_id"] = any_e["actor_id"]
        if submitted is not None:
            attributes["art.queue_time_s"] = round(
                started["ts"] - submitted["ts"], 6)
        if "failed" in ev:
            # OTel semantic convention: failed spans carry error=true on
            # top of the ERROR status code the exporters set.
            attributes["error"] = True
        spans.append(Span(
            trace_id=_trace_id(root_of(task_id)),
            span_id=_span_id(task_id, attempt),
            parent_span_id=_span_id(parent) if parent else "",
            name=any_e.get("name", task_id),
            start_ns=int(started["ts"] * _NS),
            end_ns=int(end_ts * _NS),
            ok="failed" not in ev,
            attributes=attributes,
        ))
    spans.sort(key=lambda s: s.start_ns)
    return spans


def _otlp_attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def export_otlp_json(filename: str | None = None,
                     spans: list[Span] | None = None):
    """OTLP/JSON ``resourceSpans`` payload (the shape OTLP/HTTP
    collectors and Jaeger's OTLP ingest accept); returns the dict, and
    writes it when ``filename`` is given."""
    if spans is None:
        spans = task_spans()
    payload = {"resourceSpans": [{
        "resource": {"attributes": [
            _otlp_attr("service.name", "ant_ray_tpu")]},
        "scopeSpans": [{
            "scope": {"name": "ant_ray_tpu.tasks"},
            "spans": [{
                "traceId": s.trace_id,
                "spanId": s.span_id,
                **({"parentSpanId": s.parent_span_id}
                   if s.parent_span_id else {}),
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns),
                "attributes": [_otlp_attr(k, v)
                               for k, v in s.attributes.items()],
                # STATUS_CODE_OK / STATUS_CODE_ERROR; per the OTLP spec
                # a message only accompanies ERROR.
                "status": ({"code": 2, "message": "task failed"}
                           if not s.ok else {"code": 1}),
            } for s in spans],
        }],
    }]}
    if filename:
        with open(filename, "w") as f:
            json.dump(payload, f)
        return filename
    return payload


def replay_to_otel(spans: list[Span] | None = None, tracer=None) -> int:
    """Emit spans through an installed ``opentelemetry`` SDK (optional
    dependency, like the reference's mock-when-absent behavior).

    The SDK generates its own trace/span ids, so linkage is preserved
    STRUCTURALLY: parents are emitted first and children start inside
    ``set_span_in_context(parent)`` — the backend sees the same tree
    ``task_spans`` computed, under SDK-assigned ids.  Returns the
    number of spans emitted."""
    try:
        from opentelemetry import trace as otel_trace  # noqa: PLC0415
    except ImportError as e:
        raise RuntimeError(
            "opentelemetry is not installed; use export_otlp_json() "
            "for a dependency-free OTLP payload") from e
    if spans is None:
        spans = task_spans()
    tracer = tracer or otel_trace.get_tracer("ant_ray_tpu.tasks")
    by_id = {s.span_id: s for s in spans}
    emitted: dict[str, object] = {}

    def emit(s: Span):
        if s.span_id in emitted:
            return emitted[s.span_id]
        context = None
        parent = by_id.get(s.parent_span_id)
        if parent is not None:
            context = otel_trace.set_span_in_context(emit(parent))
        span = tracer.start_span(s.name, context=context,
                                 start_time=s.start_ns,
                                 attributes=dict(s.attributes))
        if not s.ok:
            span.set_status(otel_trace.StatusCode.ERROR)
        emitted[s.span_id] = span
        return span

    for s in spans:
        emit(s)
    # End children before parents (reverse start order ≈ LIFO nesting).
    for s in sorted(spans, key=lambda s: s.start_ns, reverse=True):
        emitted[s.span_id].end(end_time=s.end_ns)
    return len(spans)
