"""Distributed tracing: task lifecycle events → OpenTelemetry spans
(ref: python/ray/util/tracing/tracing_helper.py — the reference wraps
task/actor calls in OTel spans when ``_tracing_startup_hook`` is set,
and proxy-mocks otel when it isn't installed, :147-176).

Design difference, on purpose: the reference instruments the submission
path with a live OTel SDK in every process.  Here workers already
buffer task lifecycle events (submitted/started/finished, with parent
linkage via contextvar) into the GCS aggregator for the timeline — so
spans are DERIVED from that single event stream instead of running a
second tracing pipeline.  One instrumentation, three consumers
(timeline, state API, tracing), and the OTel SDK stays optional:

* :func:`task_spans` — span objects (trace/span/parent ids, timings)
* :func:`export_otlp_json` — OTLP/JSON file any collector can ingest
* :func:`replay_to_otel` — emit through a real installed
  ``opentelemetry`` TracerProvider when the package is available
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ant_ray_tpu.util.timeline import fetch_task_events

_NS = 1_000_000_000


@dataclass
class Span:
    """One task execution, OTel-shaped."""

    trace_id: str            # 32 hex — the root task of the call tree
    span_id: str             # 16 hex — derived from the task id
    parent_span_id: str      # "" for roots
    name: str
    start_ns: int
    end_ns: int
    ok: bool = True
    attributes: dict = field(default_factory=dict)


def _span_id(task_id: str) -> str:
    # Hash, don't truncate: task ids share a long job-id prefix, so a
    # prefix-slice would collide every span in a job.
    import hashlib  # noqa: PLC0415

    return hashlib.blake2b((task_id or "").encode(),
                           digest_size=8).hexdigest()


def _trace_id(task_id: str) -> str:
    import hashlib  # noqa: PLC0415

    return hashlib.blake2b((task_id or "").encode(),
                           digest_size=16).hexdigest()


def task_spans(events: list[dict] | None = None) -> list[Span]:
    """Fold the event stream into one span per task execution.

    ``trace_id`` groups a call tree: each task inherits its root
    ancestor's id, so a driver-submitted task and everything it spawned
    share one trace (the W3C trace-context notion of the reference's
    propagated spans)."""
    if events is None:
        events = fetch_task_events()
    by_task: dict[str, dict] = {}
    for e in sorted(events, key=lambda e: e["ts"]):
        rec = by_task.setdefault(e["task_id"], {"events": {}})
        rec["events"].setdefault(e["event"], e)

    def root_of(task_id: str, hops: int = 0) -> str:
        rec = by_task.get(task_id)
        if rec is None or hops > 256:
            return task_id
        for e in rec["events"].values():
            parent = e.get("parent_task_id")
            if parent:
                return root_of(parent, hops + 1)
        return task_id

    spans = []
    for task_id, rec in by_task.items():
        ev = rec["events"]
        started = ev.get("started")
        ended = ev.get("finished") or ev.get("failed")
        submitted = ev.get("submitted")
        if started is None:
            continue  # never ran (still queued, or events truncated)
        end_ts = (ended or started)["ts"]
        any_e = started
        parent = None
        for e in ev.values():
            parent = parent or e.get("parent_task_id")
        attributes = {
            "art.task_id": task_id,
            "art.node_id": any_e.get("node_id", ""),
            "art.pid": any_e.get("pid", 0),
        }
        if any_e.get("actor_id"):
            attributes["art.actor_id"] = any_e["actor_id"]
        if submitted is not None:
            attributes["art.queue_time_s"] = round(
                started["ts"] - submitted["ts"], 6)
        if "failed" in ev:
            # OTel semantic convention: failed spans carry error=true on
            # top of the ERROR status code the exporters set.
            attributes["error"] = True
        spans.append(Span(
            trace_id=_trace_id(root_of(task_id)),
            span_id=_span_id(task_id),
            parent_span_id=_span_id(parent) if parent else "",
            name=any_e.get("name", task_id),
            start_ns=int(started["ts"] * _NS),
            end_ns=int(end_ts * _NS),
            ok="failed" not in ev,
            attributes=attributes,
        ))
    spans.sort(key=lambda s: s.start_ns)
    return spans


def _otlp_attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def export_otlp_json(filename: str | None = None,
                     spans: list[Span] | None = None):
    """OTLP/JSON ``resourceSpans`` payload (the shape OTLP/HTTP
    collectors and Jaeger's OTLP ingest accept); returns the dict, and
    writes it when ``filename`` is given."""
    if spans is None:
        spans = task_spans()
    payload = {"resourceSpans": [{
        "resource": {"attributes": [
            _otlp_attr("service.name", "ant_ray_tpu")]},
        "scopeSpans": [{
            "scope": {"name": "ant_ray_tpu.tasks"},
            "spans": [{
                "traceId": s.trace_id,
                "spanId": s.span_id,
                **({"parentSpanId": s.parent_span_id}
                   if s.parent_span_id else {}),
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns),
                "attributes": [_otlp_attr(k, v)
                               for k, v in s.attributes.items()],
                # STATUS_CODE_OK / STATUS_CODE_ERROR; per the OTLP spec
                # a message only accompanies ERROR.
                "status": ({"code": 2, "message": "task failed"}
                           if not s.ok else {"code": 1}),
            } for s in spans],
        }],
    }]}
    if filename:
        with open(filename, "w") as f:
            json.dump(payload, f)
        return filename
    return payload


def replay_to_otel(spans: list[Span] | None = None, tracer=None) -> int:
    """Emit spans through an installed ``opentelemetry`` SDK (optional
    dependency, like the reference's mock-when-absent behavior).

    The SDK generates its own trace/span ids, so linkage is preserved
    STRUCTURALLY: parents are emitted first and children start inside
    ``set_span_in_context(parent)`` — the backend sees the same tree
    ``task_spans`` computed, under SDK-assigned ids.  Returns the
    number of spans emitted."""
    try:
        from opentelemetry import trace as otel_trace  # noqa: PLC0415
    except ImportError as e:
        raise RuntimeError(
            "opentelemetry is not installed; use export_otlp_json() "
            "for a dependency-free OTLP payload") from e
    if spans is None:
        spans = task_spans()
    tracer = tracer or otel_trace.get_tracer("ant_ray_tpu.tasks")
    by_id = {s.span_id: s for s in spans}
    emitted: dict[str, object] = {}

    def emit(s: Span):
        if s.span_id in emitted:
            return emitted[s.span_id]
        context = None
        parent = by_id.get(s.parent_span_id)
        if parent is not None:
            context = otel_trace.set_span_in_context(emit(parent))
        span = tracer.start_span(s.name, context=context,
                                 start_time=s.start_ns,
                                 attributes=dict(s.attributes))
        if not s.ok:
            span.set_status(otel_trace.StatusCode.ERROR)
        emitted[s.span_id] = span
        return span

    for s in spans:
        emit(s)
    # End children before parents (reverse start order ≈ LIFO nesting).
    for s in sorted(spans, key=lambda s: s.start_ns, reverse=True):
        emitted[s.span_id].end(end_time=s.end_ns)
    return len(spans)
