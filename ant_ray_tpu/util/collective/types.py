"""Collective types (ref: python/ray/util/collective/types.py — Backend :34,
ReduceOp :55), with the NCCL backend replaced by a TPU-native ``xla``
backend lowering to XLA collectives over ICI/DCN."""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Backend:
    """Supported backends: ``xla`` (XLA collectives over ICI/DCN — the
    TPU-native replacement for NCCL) and ``gloo`` (CPU fallback over
    sockets, alias ``cpu``)."""

    XLA = "xla"
    GLOO = "gloo"

    @staticmethod
    def normalize(name: str) -> str:
        name = name.lower()
        if name in ("xla", "tpu", "ici"):
            return Backend.XLA
        if name in ("gloo", "cpu", "torch_gloo"):
            return Backend.GLOO
        if name in ("nccl", "cuda"):
            raise ValueError(
                "NCCL is not available in the TPU-native build; use "
                "backend='xla' (ICI/DCN collectives) instead")
        raise ValueError(f"Unknown collective backend {name!r}")


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    AVERAGE = "average"


@dataclass
class AllReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30_000


@dataclass
class AllReduceCoalescedOptions:
    """Knobs of the fused bucketed allreduce (util/collective/fusion.py).

    ``bucket_bytes`` — flat-buffer budget per collective (a leaf larger
    than it gets its own oversized bucket).  ``transport_dtype`` —
    opt-in reduced-precision wire format for wide float buckets
    (e.g. "bfloat16"; accumulation stays float32, EQuARX-style).
    ``overlap`` — pipeline bucket k+1's pack+transfer with bucket k's
    collective (False = sequential naive-order baseline)."""

    reduce_op: ReduceOp = ReduceOp.SUM
    bucket_bytes: int = 4 << 20
    transport_dtype: "str | None" = None
    overlap: bool = True
    timeout_ms: int = 30_000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceScatterOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30_000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30_000
