"""Collective types (ref: python/ray/util/collective/types.py — Backend :34,
ReduceOp :55), with the NCCL backend replaced by a TPU-native ``xla``
backend lowering to XLA collectives over ICI/DCN."""

from __future__ import annotations

import enum
from dataclasses import dataclass


@dataclass(frozen=True)
class SliceTopology:
    """Partition of a collective group's ranks into accelerator slices.

    Ranks inside one slice share fast interconnect (ICI); distinct
    slices talk over the datacenter network (DCN).  The hierarchical
    allreduce reduces within each slice first, exchanges once per
    *slice* across DCN, then fans back out — so the cross-slice
    message count scales with ``num_slices``, not world size.

    Hashable (tuples all the way down) so it can key compile caches.

    ``ici_bucket_bytes`` / ``dcn_bucket_bytes`` optionally override the
    fusion bucket budget per level: the intra-slice (ICI) hop is
    launch-bound, so smaller buckets pipeline better there, while the
    latency-dominated cross-slice (DCN) hop amortizes its round trips
    over larger buckets.  ``None`` inherits the caller's flat
    ``bucket_bytes``.
    """

    slices: tuple                        # tuple[tuple[int, ...], ...]
    ici_bucket_bytes: "int | None" = None
    dcn_bucket_bytes: "int | None" = None

    @property
    def num_slices(self) -> int:
        return len(self.slices)

    @staticmethod
    def regular(world_size: int, num_slices: int) -> "SliceTopology":
        """Contiguous equal partition: rank r sits in slice
        r // (world_size // num_slices)."""
        if num_slices <= 0 or world_size % num_slices != 0:
            raise ValueError(
                f"world_size {world_size} not divisible into "
                f"{num_slices} slices")
        per = world_size // num_slices
        return SliceTopology(tuple(
            tuple(range(s * per, (s + 1) * per))
            for s in range(num_slices)))

    @staticmethod
    def from_labels(pod_names) -> "SliceTopology":
        """Derive membership from each rank's ``tpu-pod-name`` node
        label (accelerators/tpu.py topology metadata): ranks on the
        same physical slice share a pod name."""
        from ant_ray_tpu._private.accelerators import tpu as tpu_accel  # noqa: PLC0415

        return SliceTopology(tuple(tpu_accel.slice_groups(pod_names)))

    def validate(self, world_size: int) -> None:
        flat = sorted(r for ranks in self.slices for r in ranks)
        if flat != list(range(world_size)):
            raise ValueError(
                f"slice topology {self.slices} is not a partition of "
                f"ranks 0..{world_size - 1}")

    def slice_of(self, rank: int) -> int:
        for sid, ranks in enumerate(self.slices):
            if rank in ranks:
                return sid
        raise ValueError(f"rank {rank} is in no slice")

    def peers(self, rank: int) -> tuple:
        return self.slices[self.slice_of(rank)]

    def leader(self, slice_id: int) -> int:
        """The slice's DCN representative (lowest rank)."""
        return min(self.slices[slice_id])

    def leaders(self) -> tuple:
        return tuple(self.leader(s) for s in range(self.num_slices))

    def per_level_bucket_bytes(self, default: int) -> tuple:
        """(ici, dcn) bucket budgets with ``default`` filling unset
        levels — the pair the fusion planner consumes."""
        return (self.ici_bucket_bytes or int(default),
                self.dcn_bucket_bytes or int(default))

    def with_bucket_bytes(self, ici: "int | None" = None,
                          dcn: "int | None" = None) -> "SliceTopology":
        """Copy with per-level fusion budgets attached (frozen
        dataclass — returns a new topology)."""
        from dataclasses import replace  # noqa: PLC0415

        return replace(self, ici_bucket_bytes=ici, dcn_bucket_bytes=dcn)


class Backend:
    """Supported backends: ``xla`` (XLA collectives over ICI/DCN — the
    TPU-native replacement for NCCL) and ``gloo`` (CPU fallback over
    sockets, alias ``cpu``)."""

    XLA = "xla"
    GLOO = "gloo"

    @staticmethod
    def normalize(name: str) -> str:
        name = name.lower()
        if name in ("xla", "tpu", "ici"):
            return Backend.XLA
        if name in ("gloo", "cpu", "torch_gloo"):
            return Backend.GLOO
        if name in ("nccl", "cuda"):
            raise ValueError(
                "NCCL is not available in the TPU-native build; use "
                "backend='xla' (ICI/DCN collectives) instead")
        raise ValueError(f"Unknown collective backend {name!r}")


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    AVERAGE = "average"


@dataclass
class AllReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30_000


@dataclass
class AllReduceCoalescedOptions:
    """Knobs of the fused bucketed allreduce (util/collective/fusion.py).

    ``bucket_bytes`` — flat-buffer budget per collective (a leaf larger
    than it gets its own oversized bucket).  ``transport_dtype`` —
    opt-in reduced-precision wire format for wide float buckets:
    ``"bfloat16"`` halves wire width, ``"int8"`` ships blockwise-scaled
    int8 codes plus a float32 scale sidecar (~0.25x the float32 wire
    bytes; SUM/AVERAGE only — other ops fall back to unquantized).
    Accumulation stays float32 either way (EQuARX-style).
    ``overlap`` — pipeline bucket k+1's pack+transfer with bucket k's
    collective (False = sequential naive-order baseline).
    ``hierarchy`` — a :class:`SliceTopology` switching the reduction to
    the two-level intra-slice (ICI) / inter-slice (DCN) schedule."""

    reduce_op: ReduceOp = ReduceOp.SUM
    bucket_bytes: int = 4 << 20
    transport_dtype: "str | None" = None
    overlap: bool = True
    hierarchy: "SliceTopology | None" = None
    timeout_ms: int = 30_000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceScatterOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30_000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30_000
