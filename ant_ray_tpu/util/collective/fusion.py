"""Fused bucketed collectives: pytree-aware allreduce coalescing.

The per-tensor verbs (``collective.allreduce``) pay fixed launch
overhead per call — compile-cache lookup, host→HBM ``device_put``,
collective dispatch, readback — so a gradient pytree with hundreds of
sub-MiB params is dominated by overhead, not bytes (T3,
arXiv:2401.16677).  This module closes that gap with the standard
bucketed flat-buffer fix:

* **Bucketing** — leaves are grouped by dtype and packed into flat
  1-D buckets of at most ``bucket_bytes`` (default 4 MiB; a single
  leaf larger than the budget gets its own bucket).  One collective
  runs per *bucket*, not per tensor.
* **Plan caching** — the flatten/unflatten layout (which leaf lands at
  which offset of which bucket) is computed once per pytree signature
  (shapes + dtypes + knobs) and LRU-cached, so steady-state training
  steps skip re-planning entirely.
* **Pipelined overlap** — the :class:`PipelinedRunner` issues bucket
  k+1's pack + host→device transfer on a producer thread while bucket
  k's collective executes on the caller's thread (double buffering,
  same discipline as ``data/device_feed.py``).
* **Reduced-precision transport** — opt-in ``transport_dtype=
  "bfloat16"`` packs float buckets at half width (halving host→HBM
  bytes); ``transport_dtype="int8"`` goes further with blockwise-scaled
  int8 quantization (one float32 scale per :data:`QUANT_BLOCK`
  elements riding a small sidecar array — ~0.25x the float32 wire
  bytes).  The reduction itself always accumulates in float32
  (EQuARX-style, arXiv:2506.17615) and results upcast back to the
  leaf dtype.
* **Gradient-ready overlap** — :class:`GradientSyncer` assigns leaves
  to buckets in *reverse* input order (reverse-topological: the last
  layers' grads, which backward materializes first, fill bucket 0) and
  launches each bucket's collective on a worker thread the moment its
  last leaf is marked ready — so wire time hides under the remaining
  backward compute (DDP-style ready hooks; T3, arXiv:2401.16677).

Every call records per-bucket stats (pack / transfer / collective /
unpack seconds, wire bytes, overlap fraction) into the owning group's
``_fusion_stats`` — surfaced via ``collective.fusion_stats()``, the
same stats idiom ``DataIterator.stats()["device_feed"]`` established.
"""

from __future__ import annotations

import functools
import queue as _queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

DEFAULT_BUCKET_BYTES = 4 << 20          # 4 MiB

# dtypes eligible for reduced-precision transport (casting ints would
# silently corrupt exact reductions).
_FLOAT_KINDS = ("f",)

#: Elements per int8 quantization block: one float32 scale per block,
#: so the sidecar adds 4/QUANT_BLOCK bytes per element (~1.6% at 256).
QUANT_BLOCK = 256

#: Reduce ops whose cross-rank combine survives blockwise int8
#: round-tripping (dequantize → accumulate at f32).  MIN/MAX/PRODUCT
#: fall back to unquantized transport.
_QUANT_OK_OPS = ("sum", "average")


def resolve_dtype(name: str) -> np.dtype:
    """np.dtype for ``name``, reaching into ml_dtypes for the narrow
    float families numpy doesn't register natively (bfloat16)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: PLC0415 — ships with jax

        return np.dtype(getattr(ml_dtypes, name))


# ----------------------------------------------------------------- plan

@dataclass(frozen=True)
class LeafSlot:
    """Where one leaf lives inside its bucket."""

    leaf_index: int
    offset: int                          # element offset into the bucket
    size: int                            # element count
    shape: tuple
    dtype: str


@dataclass(frozen=True)
class Bucket:
    """One flat dtype-homogeneous buffer."""

    dtype: str                           # logical (leaf) dtype
    transport_dtype: str                 # wire dtype (== dtype unless cast)
    size: int                            # total elements
    slots: tuple                         # tuple[LeafSlot, ...]


@dataclass(frozen=True)
class CoalescedPlan:
    buckets: tuple                       # tuple[Bucket, ...]
    n_leaves: int
    total_bytes: int


def leaf_signature(leaves) -> tuple:
    """Hashable (shape, dtype) signature of a leaf list — the plan
    cache key component.  Reads ``.shape``/``.dtype`` attributes where
    present so device-resident leaves (jax arrays) are NOT copied to
    host just to compute the key; dtype names normalize across
    frameworks ("torch.float32" → "float32")."""
    sig = []
    for leaf in leaves:
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            arr = np.asarray(leaf)
            sig.append((arr.shape, str(arr.dtype)))
        else:
            sig.append((tuple(np.shape(leaf)),
                        str(dtype).rsplit(".", 1)[-1]))
    return tuple(sig)


def _restore_leaf_type(like, arr: np.ndarray):
    """Match the naive verbs' type contract: a torch leaf comes back as
    torch, a jax leaf as a jax array, anything else as numpy."""
    module = type(like).__module__
    if module.startswith("torch"):
        import torch  # noqa: PLC0415

        try:
            return torch.from_numpy(arr)
        except TypeError:   # ml_dtypes leaf dtype: f32 bridge
            return torch.from_numpy(
                arr.astype(np.float32)).to(like.dtype)
    if module.startswith("jax"):
        import jax.numpy as jnp  # noqa: PLC0415

        return jnp.asarray(arr)
    return arr


@functools.lru_cache(maxsize=128)
def _plan_for_signature(signature: tuple, bucket_bytes: int,
                        transport_dtype: str | None,
                        reverse: bool = False) -> CoalescedPlan:
    """Pack leaves (by signature) into dtype-segregated flat buckets.

    Leaves keep their input order within a dtype so unpack is a pure
    layout lookup; a leaf larger than ``bucket_bytes`` still gets
    exactly one (oversized) bucket — coalescing must never split a
    tensor across collectives.

    ``reverse=True`` assigns leaves to buckets in reverse input order
    (reverse-topological for a params pytree: backward produces the
    LAST leaves' grads first, so bucket 0 fills — and its collective
    can launch — earliest).  Slot offsets stay layout lookups either
    way; only the bucket membership/order changes.
    """
    by_dtype: dict[str, list] = {}
    ordered = reversed(range(len(signature))) if reverse \
        else range(len(signature))
    for index in ordered:
        shape, dtype = signature[index]
        by_dtype.setdefault(dtype, []).append((index, shape))

    buckets: list[Bucket] = []
    total_bytes = 0
    for dtype, entries in by_dtype.items():
        itemsize = np.dtype(dtype).itemsize
        wire_dtype = dtype
        if (transport_dtype and np.dtype(dtype).kind in _FLOAT_KINDS
                and np.dtype(dtype).itemsize > 2):
            wire_dtype = transport_dtype
        budget = max(1, bucket_bytes // itemsize)
        slots: list[LeafSlot] = []
        offset = 0
        for index, shape in entries:
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if slots and offset + size > budget:
                buckets.append(Bucket(dtype, wire_dtype, offset,
                                      tuple(slots)))
                slots, offset = [], 0
            slots.append(LeafSlot(index, offset, size, tuple(shape), dtype))
            offset += size
            total_bytes += size * itemsize
        if slots:
            buckets.append(Bucket(dtype, wire_dtype, offset, tuple(slots)))
    return CoalescedPlan(tuple(buckets), len(signature), total_bytes)


def plan_buckets(leaves, bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 transport_dtype: str | None = None, *,
                 reverse: bool = False) -> CoalescedPlan:
    return _plan_for_signature(leaf_signature(leaves), int(bucket_bytes),
                               transport_dtype, reverse)


def plan_buckets_per_level(leaves, topo, bucket_bytes: int =
                           DEFAULT_BUCKET_BYTES,
                           transport_dtype: str | None = None, *,
                           reverse: bool = False) -> dict:
    """Per-level plans for a hierarchical reduction: the intra-slice
    (ICI) hop packs at ``topo.ici_bucket_bytes`` and the cross-slice
    (DCN) leader exchange at ``topo.dcn_bucket_bytes`` (each defaulting
    to ``bucket_bytes``).  The ICI plan is the wire plan — pack/unpack
    layout and per-bucket launch granularity — while the DCN plan
    bounds how many ICI buckets the leader hop may batch per exchange
    (typically fewer, larger buckets: DCN round trips cost more than
    they stream)."""
    ici_bytes, dcn_bytes = topo.per_level_bucket_bytes(bucket_bytes)
    signature = leaf_signature(leaves)
    return {
        "ici": _plan_for_signature(signature, int(ici_bytes),
                                   transport_dtype, reverse),
        "dcn": _plan_for_signature(signature, int(dcn_bytes),
                                   transport_dtype, reverse),
    }


def plan_cache_info():
    return _plan_for_signature.cache_info()


# ------------------------------------------------- int8 wire quantization

def quant_blocks(size: int, block: int = QUANT_BLOCK) -> int:
    """Number of scale blocks covering ``size`` elements (final block
    may be odd-sized)."""
    return max(1, -(-size // block))


def quantize_blockwise(flat: np.ndarray, block: int = QUANT_BLOCK
                       ) -> tuple[np.ndarray, np.ndarray]:
    """float → (int8 codes, per-block float32 scales).

    Each block of ``block`` elements is scaled by max(|x|)/127 so the
    widest element maps to ±127; an all-zero block keeps scale 1.0
    (codes are 0, avoiding 0-division on dequant).  The sidecar costs
    4/block bytes per element — ~1.6% at the default 256."""
    flat = np.asarray(flat, dtype=np.float32).reshape(-1)
    size = flat.size
    n_blocks = quant_blocks(size, block)
    padded = np.zeros((n_blocks * block,), np.float32)
    padded[:size] = flat
    grid = padded.reshape(n_blocks, block)
    scales = np.abs(grid).max(axis=1) / 127.0
    scales[scales == 0.0] = 1.0
    scales = scales.astype(np.float32)
    q = np.clip(np.rint(grid / scales[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1)[:size], scales


def dequantize_blockwise(q: np.ndarray, scales: np.ndarray,
                         block: int = QUANT_BLOCK) -> np.ndarray:
    """(int8 codes, per-block scales) → float32 values."""
    q = np.asarray(q, dtype=np.int8).reshape(-1)
    scales = np.asarray(scales, dtype=np.float32).reshape(-1)
    expanded = np.repeat(scales, block)[:q.size]
    return q.astype(np.float32) * expanded


def payload_nbytes(payload) -> int:
    """Wire bytes of a packed bucket payload (plain array or the
    int8 ``(codes, scales)`` pair)."""
    if isinstance(payload, tuple):
        return sum(int(np.asarray(p).nbytes) for p in payload)
    return int(np.asarray(payload).nbytes)


def pack_bucket(bucket: Bucket, leaves):
    """Leaves → one contiguous flat buffer in the bucket's wire dtype.

    The transport cast (e.g. float32→bfloat16) happens HERE, once, on
    the host — that is the lossy step; the reduction itself accumulates
    at float32 (see the backend paths).  An ``int8`` transport bucket
    returns the ``(codes, scales)`` pair from
    :func:`quantize_blockwise` instead of a single array."""
    quantized = bucket.transport_dtype == "int8"
    pack_dtype = (np.dtype(np.float32) if quantized
                  else resolve_dtype(bucket.transport_dtype))
    flat = np.empty((bucket.size,), dtype=pack_dtype)
    for slot in bucket.slots:
        leaf = leaves[slot.leaf_index]
        try:
            arr = np.asarray(leaf)
        except TypeError:   # torch bfloat16: no direct numpy bridge
            arr = np.asarray(leaf.float())
        flat[slot.offset:slot.offset + slot.size] = (
            arr.reshape(-1).astype(flat.dtype, copy=False))
    if quantized:
        return quantize_blockwise(flat)
    return flat


def unpack_bucket(bucket: Bucket, flat, out: list) -> None:
    """Reduced flat buffer → per-leaf arrays (leaf dtype restored) into
    ``out`` at each slot's original pytree position."""
    flat = np.asarray(flat)
    leaf_dtype = np.dtype(bucket.dtype)
    for slot in bucket.slots:
        piece = flat[slot.offset:slot.offset + slot.size]
        out[slot.leaf_index] = np.ascontiguousarray(
            piece.astype(leaf_dtype, copy=False).reshape(slot.shape))


# ------------------------------------------------------------- pipeline

class PipelinedRunner:
    """Two-stage pipeline over an item list: ``prepare`` (pack +
    transfer issue) for item k+1 overlaps ``collective`` for item k.

    ``prepare_fn(item, index)`` runs on a producer thread feeding a
    bounded queue (depth 1 = classic double buffering); the caller's
    thread drains it through ``collective_fn(staged, index)``.  With
    ``overlap=False`` both stages run inline — the naive baseline.

    ``clock`` is injectable (tests drive a logical counter — no
    wall-clock flakiness); every stage edge is appended to ``events``
    as ``(stage_edge, index, tick)`` and :meth:`overlap_seconds`
    integrates prepare∩collective window intersections.
    """

    def __init__(self, prepare_fn, collective_fn, *, overlap: bool = True,
                 depth: int = 1, clock=time.perf_counter):
        self._prepare = prepare_fn
        self._collective = collective_fn
        self._overlap = overlap
        self._depth = max(1, depth)
        self._clock = clock
        self._lock = threading.Lock()
        self.events: list = []

    def _mark(self, edge: str, index: int) -> None:
        with self._lock:
            self.events.append((edge, index, self._clock()))

    def _staged_prepare(self, item, index: int):
        self._mark("prepare_start", index)
        try:
            return self._prepare(item, index)
        finally:
            self._mark("prepare_end", index)

    def _run_collective(self, staged, index: int):
        self._mark("collective_start", index)
        try:
            return self._collective(staged, index)
        finally:
            self._mark("collective_end", index)

    def run(self, items) -> list:
        items = list(items)
        if not items:
            return []
        if not self._overlap or len(items) == 1:
            return [self._run_collective(self._staged_prepare(item, k), k)
                    for k, item in enumerate(items)]

        q: _queue.Queue = _queue.Queue(maxsize=self._depth)
        stop = threading.Event()

        def produce():
            for k, item in enumerate(items):
                try:
                    staged = ("item", self._staged_prepare(item, k))
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    staged = ("error", e)
                while not stop.is_set():
                    try:
                        q.put(staged, timeout=0.05)
                        break
                    except _queue.Full:
                        continue
                if stop.is_set() or staged[0] == "error":
                    return

        producer = threading.Thread(target=produce, daemon=True,
                                    name="coalesced-prepare")
        producer.start()
        results = []
        try:
            for k in range(len(items)):
                kind, staged = q.get()
                if kind == "error":
                    raise staged
                results.append(self._run_collective(staged, k))
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            producer.join(timeout=5.0)
        return results

    # ---- stats

    def _windows(self, stage: str) -> list:
        starts: dict[int, float] = {}
        spans = []
        for edge, index, tick in self.events:
            if edge == f"{stage}_start":
                starts[index] = tick
            elif edge == f"{stage}_end" and index in starts:
                spans.append((starts.pop(index), tick))
        return spans

    def overlap_seconds(self) -> float:
        """Total prepare time spent inside some collective window."""
        collectives = self._windows("collective")
        overlapped = 0.0
        for p0, p1 in self._windows("prepare"):
            for c0, c1 in collectives:
                overlapped += max(0.0, min(p1, c1) - max(p0, c0))
        return overlapped

    def stage_seconds(self, stage: str) -> float:
        return sum(t1 - t0 for t0, t1 in self._windows(stage))


# ------------------------------------------------------------ execution

@dataclass
class FusionStats:
    """Cumulative per-group fusion counters (device_feed stats idiom).

    ``wire_bytes`` is what actually crossed the wire (post transport
    cast / quantization, sidecar scales included) vs ``bytes`` which is
    the logical leaf payload.  ``dcn_participants`` counts ranks that
    took part in a cross-slice (DCN) exchange, cumulative per bucket
    collective: a flat allreduce adds world_size, the hierarchical path
    adds num_slices — their ratio is the hierarchy's win.
    ``overlap_s`` is collective time hidden under concurrent pack/
    transfer (pipelined path) or remaining backward compute
    (:class:`GradientSyncer`); ``overlap_fraction`` is the hidden share
    of total collective time."""

    calls: int = 0
    tensors: int = 0
    buckets: int = 0
    bytes: int = 0
    wire_bytes: int = 0
    dcn_participants: int = 0
    pack_s: float = 0.0
    transfer_s: float = 0.0
    collective_s: float = 0.0
    unpack_s: float = 0.0
    overlap_s: float = 0.0
    plan_cache_hits: int = 0
    last: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "calls": self.calls,
            "tensors": self.tensors,
            "buckets": self.buckets,
            "bytes": self.bytes,
            "wire_bytes": self.wire_bytes,
            "wire_ratio": (self.wire_bytes / self.bytes
                           if self.bytes > 0 else 1.0),
            "dcn_participants": self.dcn_participants,
            "pack_s": self.pack_s,
            "transfer_s": self.transfer_s,
            "collective_s": self.collective_s,
            "unpack_s": self.unpack_s,
            "overlap_s": self.overlap_s,
            "overlap_fraction": (min(1.0, self.overlap_s
                                     / self.collective_s)
                                 if self.collective_s > 0 else 0.0),
            "plan_cache_hits": self.plan_cache_hits,
            "last": dict(self.last),
        }


def effective_transport(opts) -> str | None:
    """The transport dtype actually usable for this reduce op: int8's
    dequantize-then-accumulate combine only composes for SUM/AVERAGE
    (MIN/MAX/PRODUCT fall back to unquantized transport)."""
    transport = opts.transport_dtype
    if transport == "int8":
        op = getattr(opts.reduce_op, "value", str(opts.reduce_op))
        if op not in _QUANT_OK_OPS:
            return None
    return transport


def run_coalesced(tensors, opts, *, transfer_fn, collective_fn,
                  stats: FusionStats | None = None) -> list:
    """Shared engine for the backend ``allreduce_coalesced`` verbs.

    ``transfer_fn(flat, bucket)`` stages a packed host buffer toward
    the backend (host→HBM ``device_put`` for xla, torch wrap for gloo)
    — it runs on the pipeline's producer thread so bucket k+1's
    transfer overlaps bucket k's collective.  ``collective_fn(staged,
    bucket)`` performs one fused reduction and returns the reduced
    flat buffer (any array type ``np.asarray`` accepts).
    """
    tensors = list(tensors)
    if not tensors:
        return []
    transport = effective_transport(opts)
    hits_before = _plan_for_signature.cache_info().hits
    topo = getattr(opts, "hierarchy", None)
    level_buckets = None
    if topo is not None and (topo.ici_bucket_bytes
                             or topo.dcn_bucket_bytes):
        # Hierarchical with per-level budgets: the ICI plan IS the
        # wire plan (pack layout + intra-slice launch granularity);
        # the coarser DCN plan is recorded so the leader hop's
        # batching headroom is visible in stats.
        levels = plan_buckets_per_level(tensors, topo,
                                        opts.bucket_bytes, transport)
        plan = levels["ici"]
        level_buckets = {"ici": len(levels["ici"].buckets),
                         "dcn": len(levels["dcn"].buckets)}
    else:
        plan = plan_buckets(tensors, opts.bucket_bytes, transport)
    plan_hit = _plan_for_signature.cache_info().hits > hits_before

    timings = {"pack_s": 0.0, "transfer_s": 0.0, "collective_s": 0.0}
    wire = {"bytes": 0}
    lock = threading.Lock()

    def prepare(bucket: Bucket, _index: int):
        t0 = time.perf_counter()
        flat = pack_bucket(bucket, tensors)
        t1 = time.perf_counter()
        staged = transfer_fn(flat, bucket)
        t2 = time.perf_counter()
        with lock:
            timings["pack_s"] += t1 - t0
            timings["transfer_s"] += t2 - t1
            wire["bytes"] += payload_nbytes(flat)
        return bucket, staged

    def reduce_one(staged, _index: int):
        bucket, payload = staged
        t0 = time.perf_counter()
        out = collective_fn(payload, bucket)
        with lock:
            timings["collective_s"] += time.perf_counter() - t0
        return bucket, out

    runner = PipelinedRunner(prepare, reduce_one, overlap=opts.overlap)
    reduced = runner.run(plan.buckets)

    t0 = time.perf_counter()
    out: list = [None] * plan.n_leaves
    for bucket, flat in reduced:
        unpack_bucket(bucket, flat, out)
    out = [_restore_leaf_type(leaf, arr)
           for leaf, arr in zip(tensors, out)]
    unpack_s = time.perf_counter() - t0

    if stats is not None:
        overlap_s = runner.overlap_seconds()
        last = {
            "tensors": plan.n_leaves,
            "buckets": len(plan.buckets),
            "bytes": plan.total_bytes,
            "wire_bytes": wire["bytes"],
            "transport_dtype": transport or "",
            "plan_cache_hit": plan_hit,
            "overlap_s": overlap_s,
            "unpack_s": unpack_s,
            **timings,
        }
        if level_buckets is not None:
            last["level_buckets"] = level_buckets
        stats.calls += 1
        stats.tensors += plan.n_leaves
        stats.buckets += len(plan.buckets)
        stats.bytes += plan.total_bytes
        stats.wire_bytes += wire["bytes"]
        stats.pack_s += timings["pack_s"]
        stats.transfer_s += timings["transfer_s"]
        stats.collective_s += timings["collective_s"]
        stats.unpack_s += unpack_s
        stats.overlap_s += overlap_s
        stats.plan_cache_hits += int(plan_hit)
        stats.last = last
    return out


# ----------------------------------------------------- gradient overlap

class GradientSyncer:
    """DDP-style gradient-ready overlap over a collective group.

    ``begin(template)`` plans buckets from the grads pytree in
    *reverse* leaf order (backward produces the last layers' grads
    first, so bucket 0 completes earliest) and starts a worker thread
    that processes buckets strictly in plan order: wait until every
    leaf of bucket k is marked ready, pack, stage the transfer, run the
    collective.  The fixed order keeps cross-rank launch order
    deterministic — every rank reduces bucket k before bucket k+1 —
    while bucket k's wire time hides under the compute still producing
    bucket k+1's leaves.

    The caller marks leaves via ``ready(leaf_index, grad)`` as backward
    materializes them (leaf indices follow ``flatten_pytree`` order)
    and collects the reduced pytree with ``wait()``.  ``sync(tree)`` is
    the one-shot degenerate case: every leaf is ready up front, so it
    behaves like ``allreduce_coalesced`` with reverse bucket order —
    the signature ``train.sync_gradients`` keeps.

    Overlap accounting rides the :class:`PipelinedRunner` tick
    machinery (``_mark`` / ``_windows`` with an injectable clock): the
    compute window spans ``begin()`` → ``wait()`` entry, and collective
    time inside it was hidden under backward — fed into the group's
    ``FusionStats.overlap_s`` and the ``overlap_fraction`` the step
    profiler and ``art_train_step_phase_fraction{collective}`` gauge
    consume.
    """

    def __init__(self, group, opts, *, clock=time.perf_counter):
        self._group = group
        self._opts = opts
        self._clock = clock
        self._state: dict | None = None

    # ------------------------------------------------------------ begin

    def begin(self, template) -> "GradientSyncer":
        """Plan buckets from ``template`` (the grads pytree — shapes
        and dtypes are read, values ignored) and start the bucket
        worker.  One sync may be in flight at a time."""
        if self._state is not None:
            raise RuntimeError("a gradient sync is already in flight; "
                               "call wait() first")
        leaves, treedef = flatten_pytree(template)
        transport = effective_transport(self._opts)
        hits_before = _plan_for_signature.cache_info().hits
        plan = plan_buckets(leaves, self._opts.bucket_bytes, transport,
                            reverse=True)
        plan_hit = _plan_for_signature.cache_info().hits > hits_before
        bucket_of = {}
        remaining = []
        for bi, bucket in enumerate(plan.buckets):
            remaining.append(len(bucket.slots))
            for slot in bucket.slots:
                bucket_of[slot.leaf_index] = bi
        runner = PipelinedRunner(None, None, clock=self._clock)
        state = {
            "plan": plan, "treedef": treedef, "plan_hit": plan_hit,
            "leaves": leaves, "values": list(leaves),
            "bucket_of": bucket_of, "remaining": remaining,
            "bucket_ready": [threading.Event() for _ in plan.buckets],
            "reduced": [None] * len(plan.buckets),
            "wire_bytes": 0, "error": None,
            "runner": runner, "lock": threading.Lock(),
            "timings": {"pack_s": 0.0, "transfer_s": 0.0,
                        "collective_s": 0.0},
        }
        runner._mark("compute_start", 0)
        thread = threading.Thread(target=self._drain, args=(state,),
                                  daemon=True, name="gradient-syncer")
        state["thread"] = thread
        self._state = state
        thread.start()
        return self

    def _drain(self, state: dict) -> None:
        runner: PipelinedRunner = state["runner"]
        timings = state["timings"]
        try:
            for bi, bucket in enumerate(state["plan"].buckets):
                state["bucket_ready"][bi].wait()
                t0 = time.perf_counter()
                runner._mark("prepare_start", bi)
                flat = pack_bucket(bucket, state["values"])
                t1 = time.perf_counter()
                staged = self._group.bucket_transfer(flat, bucket,
                                                     self._opts)
                runner._mark("prepare_end", bi)
                t2 = time.perf_counter()
                runner._mark("collective_start", bi)
                out = self._group.bucket_reduce(staged, bucket,
                                                self._opts)
                runner._mark("collective_end", bi)
                t3 = time.perf_counter()
                with state["lock"]:
                    timings["pack_s"] += t1 - t0
                    timings["transfer_s"] += t2 - t1
                    timings["collective_s"] += t3 - t2
                    state["wire_bytes"] += payload_nbytes(flat)
                state["reduced"][bi] = out
        except BaseException as e:  # noqa: BLE001 — re-raised by wait()
            state["error"] = e

    # ------------------------------------------------------------ ready

    def ready(self, leaf_index: int, grad=None) -> None:
        """Mark one leaf's gradient as materialized (optionally
        replacing the template's value).  When a bucket's last leaf
        arrives its collective becomes eligible immediately."""
        state = self._state
        if state is None:
            raise RuntimeError("no gradient sync in flight; call begin()")
        bi = state["bucket_of"].get(leaf_index)
        if bi is None:
            raise IndexError(f"leaf index {leaf_index} is not in the plan")
        with state["lock"]:
            if grad is not None:
                state["values"][leaf_index] = grad
            state["remaining"][bi] -= 1
            fire = state["remaining"][bi] == 0
        if fire:
            state["bucket_ready"][bi].set()

    def wait(self):
        """Block until every bucket reduced; unpack and return the
        synced pytree.  Collective windows that closed before this call
        were fully hidden under backward compute."""
        state = self._state
        if state is None:
            raise RuntimeError("no gradient sync in flight; call begin()")
        runner: PipelinedRunner = state["runner"]
        runner._mark("compute_end", 0)
        state["thread"].join()
        self._state = None
        if state["error"] is not None:
            raise state["error"]

        plan: CoalescedPlan = state["plan"]
        t0 = time.perf_counter()
        out: list = [None] * plan.n_leaves
        for bucket, flat in zip(plan.buckets, state["reduced"]):
            unpack_bucket(bucket, flat, out)
        out = [_restore_leaf_type(leaf, arr)
               for leaf, arr in zip(state["leaves"], out)]
        unpack_s = time.perf_counter() - t0

        compute = runner._windows("compute")
        overlap_s = 0.0
        for c0, c1 in runner._windows("collective"):
            for w0, w1 in compute:
                overlap_s += max(0.0, min(c1, w1) - max(c0, w0))
        stats = getattr(self._group, "_fusion_stats", None)
        if stats is None:
            stats = self._group._fusion_stats = FusionStats()
        timings = state["timings"]
        stats.calls += 1
        stats.tensors += plan.n_leaves
        stats.buckets += len(plan.buckets)
        stats.bytes += plan.total_bytes
        stats.wire_bytes += state["wire_bytes"]
        stats.pack_s += timings["pack_s"]
        stats.transfer_s += timings["transfer_s"]
        stats.collective_s += timings["collective_s"]
        stats.unpack_s += unpack_s
        stats.overlap_s += overlap_s
        stats.plan_cache_hits += int(state["plan_hit"])
        stats.last = {
            "tensors": plan.n_leaves, "buckets": len(plan.buckets),
            "bytes": plan.total_bytes,
            "wire_bytes": state["wire_bytes"],
            "transport_dtype": effective_transport(self._opts) or "",
            "plan_cache_hit": state["plan_hit"],
            "overlap_s": overlap_s, "unpack_s": unpack_s,
            "collective_s_clock": runner.stage_seconds("collective"),
            **timings,
        }
        return unflatten_pytree(state["treedef"], out)

    # --------------------------------------------------------- one-shot

    def sync(self, tree):
        """One-shot sync: every leaf is already materialized — the
        degenerate case with reverse bucket order and identical
        numerics to the hook-driven path."""
        self.begin(tree)
        state = self._state
        for leaf_index in reversed(range(len(state["leaves"]))):
            self.ready(leaf_index)
        return self.wait()


# -------------------------------------------------------------- pytree

def flatten_pytree(tree):
    """Deterministic flatten for dict/list/tuple pytrees (jax
    tree_util when importable — matches jax training code — with a
    pure-python fallback so the gloo path never needs jax)."""
    try:
        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

        jax = import_jax()
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return leaves, ("jax", treedef)
    except Exception:  # noqa: BLE001 — host-only rig
        leaves: list = []

        def walk(node):
            if isinstance(node, dict):
                return ("dict", [(k, walk(node[k]))
                                 for k in sorted(node)])
            if isinstance(node, (list, tuple)):
                return (type(node).__name__, [walk(v) for v in node])
            leaves.append(node)
            return ("leaf", len(leaves) - 1)

        spec = walk(tree)
        return leaves, ("py", spec)


def unflatten_pytree(treedef, leaves):
    kind, spec = treedef
    if kind == "jax":
        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

        return import_jax().tree_util.tree_unflatten(spec, leaves)

    def build(node):
        tag, payload = node
        if tag == "dict":
            return {k: build(v) for k, v in payload}
        if tag == "list":
            return [build(v) for v in payload]
        if tag == "tuple":
            return tuple(build(v) for v in payload)
        return leaves[payload]

    return build(spec)
