"""Gloo (CPU) collective group via torch.distributed — the cross-process
fallback over sockets/DCN (ref: python/ray/util/collective/collective_group/
torch_gloo_collective_group.py).  Rendezvous of the TCP store rides the GCS
KV instead of a named store actor."""

from __future__ import annotations

import logging
import time

import numpy as np

from ant_ray_tpu.util.collective import types
from ant_ray_tpu.util.collective.collective_group.base import BaseGroup

logger = logging.getLogger(__name__)

_REDUCE_MAP = None


def _dist():
    import torch.distributed as dist  # noqa: PLC0415

    global _REDUCE_MAP
    if _REDUCE_MAP is None:
        _REDUCE_MAP = {
            types.ReduceOp.SUM: dist.ReduceOp.SUM,
            types.ReduceOp.PRODUCT: dist.ReduceOp.PRODUCT,
            types.ReduceOp.MIN: dist.ReduceOp.MIN,
            types.ReduceOp.MAX: dist.ReduceOp.MAX,
            types.ReduceOp.AVERAGE: dist.ReduceOp.AVG,
        }
    return dist


class GlooGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str,
                 init_method: str):
        super().__init__(world_size, rank, group_name)
        dist = _dist()
        if dist.is_initialized():
            raise RuntimeError(
                "torch.distributed already initialized in this process; "
                "only one live gloo group per process is supported "
                "(destroy the existing group first)")
        dist.init_process_group(
            "gloo", init_method=init_method, rank=rank,
            world_size=world_size)
        # topology.slices -> prebuilt intra-slice / leaders subgroups
        self._hier_cache: dict = {}

    @classmethod
    def backend(cls):
        return "gloo"

    def destroy_group(self):
        dist = _dist()
        if dist.is_initialized():
            dist.destroy_process_group()

    # ---- torch/jax/numpy interop

    @staticmethod
    def _to_torch(tensor):
        import torch  # noqa: PLC0415

        if isinstance(tensor, torch.Tensor):
            return tensor, ("torch", None)
        arr = np.asarray(tensor)
        origin = type(tensor).__module__
        try:
            return torch.from_numpy(arr.copy()), (origin, None)
        except TypeError:
            # Narrow floats the numpy↔torch bridge rejects (ml_dtypes
            # bfloat16): reduce at float32, restore dtype on the way out.
            return (torch.from_numpy(arr.astype(np.float32)),
                    (origin, arr.dtype))

    @staticmethod
    def _from_torch(t, origin):
        module, cast = origin if isinstance(origin, tuple) else (origin,
                                                                 None)
        if module == "torch":
            return t
        out = t.numpy()
        if cast is not None:
            out = out.astype(cast)
        if module.startswith("jax"):
            import jax.numpy as jnp  # noqa: PLC0415

            return jnp.asarray(out)
        return out

    # ---- verbs

    def allreduce(self, tensors, opts: types.AllReduceOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.all_reduce(t, op=_REDUCE_MAP[opts.reduce_op])
        return [self._from_torch(t, origin)]

    def allreduce_coalesced(self, tensors,
                            opts: types.AllReduceCoalescedOptions):
        """Fused path: one flat torch tensor (and one ``all_reduce``)
        per dtype-segregated bucket instead of a per-tensor loop — the
        per-call gloo round trip is paid ~#buckets times, not #tensors
        times.  A reduced-precision bucket (``transport_dtype``) was
        quantized once at pack time; the reduction itself runs at
        float32 (accumulate-in-f32, EQuARX-style).  With
        ``opts.hierarchy`` the reduction runs the two-level intra-slice
        / inter-slice schedule (see :meth:`bucket_reduce`)."""
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        if getattr(self, "_fusion_stats", None) is None:
            self._fusion_stats = fusion.FusionStats()

        def transfer(flat, bucket):
            return self.bucket_transfer(flat, bucket, opts)

        def reduce_bucket(staged, bucket):
            return self.bucket_reduce(staged, bucket, opts)

        return fusion.run_coalesced(tensors, opts, transfer_fn=transfer,
                                    collective_fn=reduce_bucket,
                                    stats=self._fusion_stats)

    # ---- per-bucket stages (driven by run_coalesced AND GradientSyncer)

    def bucket_transfer(self, flat, bucket,
                        opts: types.AllReduceCoalescedOptions):
        import torch  # noqa: PLC0415

        if bucket.transport_dtype == "int8":
            # pack_bucket produced (codes, scales); ship both as one
            # contiguous byte tensor — THESE are the wire bytes
            # (≈ size + 4·size/QUANT_BLOCK vs 4·size for float32).
            q, scales = flat
            wire = np.concatenate([q.view(np.uint8),
                                   scales.view(np.uint8)])
            return torch.from_numpy(wire)
        if bucket.transport_dtype != bucket.dtype:
            # The lossy cast already happened in pack_bucket;
            # upcast so gloo accumulates at full precision.
            flat = flat.astype(np.float32)
        try:
            return torch.from_numpy(flat)   # zero-copy wrap
        except TypeError:
            # ml_dtypes bucket (bfloat16 leaves): float32 bridge —
            # unpack restores the leaf dtype.
            return torch.from_numpy(flat.astype(np.float32))

    def bucket_reduce(self, staged, bucket,
                      opts: types.AllReduceCoalescedOptions):
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        if getattr(self, "_fusion_stats", None) is None:
            self._fusion_stats = fusion.FusionStats()
        stats = self._fusion_stats
        hier = self._hier_state(opts)
        if bucket.transport_dtype == "int8":
            return self._reduce_bucket_q8(staged, bucket, opts, hier,
                                          stats)
        return self._reduce_bucket_plain(staged, opts, hier, stats)

    # ---- hierarchical (two-level) schedule

    def _hier_state(self, opts) -> dict | None:
        """Prebuilt torch.distributed subgroups for ``opts.hierarchy``,
        or None when the topology degenerates to flat.  Every rank
        creates every subgroup in the same deterministic order (a
        ``dist.new_group`` contract); results are cached per topology.
        """
        topo = getattr(opts, "hierarchy", None)
        if (topo is None or self._world_size == 1
                or topo.num_slices <= 1):
            return None
        state = self._hier_cache.get(topo.slices)
        if state is not None:
            return state
        dist = _dist()
        topo.validate(self._world_size)
        my_slice = topo.slice_of(self._rank)
        intra_group = None
        for sid, ranks in enumerate(topo.slices):
            group = dist.new_group(list(ranks))
            if sid == my_slice:
                intra_group = group
        leaders_group = dist.new_group(list(topo.leaders()))
        state = {
            "topo": topo,
            "intra": intra_group,
            "intra_ranks": topo.slices[my_slice],
            "leaders": leaders_group,
            "leader_rank": topo.leader(my_slice),
            "is_leader": self._rank == topo.leader(my_slice),
        }
        self._hier_cache[topo.slices] = state
        return state

    def _reduce_bucket_plain(self, t, opts, hier, stats):
        """Full-precision (or bf16-upcast) bucket reduction.  Flat: one
        world-wide all_reduce.  Hierarchical: reduce inside each slice
        (the ICI-analog hop), exchange once per *slice* between slice
        leaders (the DCN hop — num_slices participants, not
        world_size), then fan the result back out within each slice."""
        dist = _dist()
        if hier is None:
            dist.all_reduce(t, op=_REDUCE_MAP[opts.reduce_op])
            stats.dcn_participants += self._world_size
            return t.numpy()
        average = opts.reduce_op == types.ReduceOp.AVERAGE
        # AVERAGE averaged per level would double-divide; SUM both
        # levels and divide once at the end.  MIN/MAX/PRODUCT compose
        # level-wise unchanged.
        level_op = _REDUCE_MAP[types.ReduceOp.SUM if average
                               else opts.reduce_op]
        intra_n = len(hier["intra_ranks"])
        if intra_n > 1:
            dist.all_reduce(t, op=level_op, group=hier["intra"])
        if hier["is_leader"]:
            dist.all_reduce(t, op=level_op, group=hier["leaders"])
        if intra_n > 1:
            dist.broadcast(t, src=hier["leader_rank"],
                           group=hier["intra"])
        if average:
            t = t / self._world_size
        stats.dcn_participants += hier["topo"].num_slices
        return t.numpy()

    # ---- int8 blockwise-quantized wire

    def _split_q8(self, wire: np.ndarray, size: int):
        """One wire byte buffer → (int8 codes, float32 scales)."""
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        n_blocks = fusion.quant_blocks(size)
        codes = wire[:size].view(np.int8)
        scales = wire[size:size + 4 * n_blocks].view(np.float32)
        return codes, scales

    def _gather_dequant_sum(self, wire_t, size: int, group, n: int
                            ) -> np.ndarray:
        """all_gather the quantized wire buffers of ``n`` peers (int8
        codes + scales — the only bytes that cross this link), then
        dequantize each contribution and accumulate at float32
        (EQuARX-style: the wire is narrow, the math is not)."""
        import torch  # noqa: PLC0415

        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        dist = _dist()
        if n == 1:
            codes, scales = self._split_q8(wire_t.numpy(), size)
            return fusion.dequantize_blockwise(codes, scales)
        bufs = [torch.empty_like(wire_t) for _ in range(n)]
        if group is None:
            dist.all_gather(bufs, wire_t)
        else:
            dist.all_gather(bufs, wire_t, group=group)
        acc: np.ndarray | None = None
        for buf in bufs:
            codes, scales = self._split_q8(buf.numpy(), size)
            part = fusion.dequantize_blockwise(codes, scales)
            acc = part if acc is None else acc + part
        return acc

    def _reduce_bucket_q8(self, wire_t, bucket, opts, hier, stats):
        """Quantized bucket reduction: peers exchange int8 codes +
        scales and every rank accumulates the dequantized contributions
        at float32.  Hierarchical: the intra-slice gather runs within
        the slice, then each slice LEADER re-quantizes its partial sum
        for the once-per-slice DCN exchange and fans the float32 result
        back out."""
        import torch  # noqa: PLC0415

        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        dist = _dist()
        size = bucket.size
        average = opts.reduce_op == types.ReduceOp.AVERAGE
        if hier is None:
            acc = self._gather_dequant_sum(wire_t, size, None,
                                           self._world_size)
            stats.dcn_participants += self._world_size
        else:
            intra_n = len(hier["intra_ranks"])
            acc = self._gather_dequant_sum(wire_t, size, hier["intra"],
                                           intra_n)
            if hier["is_leader"]:
                q2, s2 = fusion.quantize_blockwise(acc)
                wire2 = torch.from_numpy(np.concatenate(
                    [q2.view(np.uint8), s2.view(np.uint8)]))
                acc = self._gather_dequant_sum(
                    wire2, size, hier["leaders"],
                    hier["topo"].num_slices)
            if intra_n > 1:
                acc_t = torch.from_numpy(
                    np.ascontiguousarray(acc, dtype=np.float32))
                dist.broadcast(acc_t, src=hier["leader_rank"],
                               group=hier["intra"])
                acc = acc_t.numpy()
            stats.dcn_participants += hier["topo"].num_slices
        if average:
            acc = acc / self._world_size
        return acc

    def barrier(self, opts: types.BarrierOptions):
        _dist().barrier()

    def reduce(self, tensors, opts: types.ReduceOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.reduce(t, dst=opts.root_rank, op=_REDUCE_MAP[opts.reduce_op])
        return [self._from_torch(t, origin)]

    def broadcast(self, tensors, opts: types.BroadcastOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.broadcast(t, src=opts.root_rank)
        return [self._from_torch(t, origin)]

    def allgather(self, tensors, opts: types.AllGatherOptions):
        import torch  # noqa: PLC0415

        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        out = [torch.empty_like(t) for _ in range(self._world_size)]
        dist.all_gather(out, t)
        return [[self._from_torch(o, origin) for o in out]]

    def reducescatter(self, tensors, opts: types.ReduceScatterOptions):
        import torch  # noqa: PLC0415

        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        if t.shape[0] % self._world_size != 0:
            raise ValueError("reducescatter needs dim0 divisible by world")
        dist.all_reduce(t, op=_REDUCE_MAP[opts.reduce_op])
        chunk = t.shape[0] // self._world_size
        piece = t[self._rank * chunk:(self._rank + 1) * chunk]
        return [self._from_torch(piece, origin)]

    def send(self, tensors, opts: types.SendOptions):
        dist = _dist()
        t, _origin = self._to_torch(tensors[0])
        dist.send(t, dst=opts.dst_rank)

    def recv(self, tensors, opts: types.RecvOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.recv(t, src=opts.src_rank)
        return [self._from_torch(t, origin)]


def rendezvous_init_method(group_name: str, rank: int,
                           timeout_s: float = 60.0) -> str:
    """Agree on a TCP init method via GCS KV (replaces the reference's
    named-actor NCCLUniqueID rendezvous, nccl_collective_group.py:29-78)."""
    from ant_ray_tpu._private.protocol import find_free_port  # noqa: PLC0415
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    key = f"collective:{group_name}:init_method"
    if rank == 0:
        method = f"tcp://127.0.0.1:{find_free_port()}"
        runtime._gcs.call("KVPut", {"key": key, "value": method.encode()})
        return method
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = runtime._gcs.call("KVGet", {"key": key})
        if value is not None:
            return value.decode()
        time.sleep(0.05)
    raise TimeoutError(f"rendezvous for group {group_name!r} timed out")
