"""Gloo (CPU) collective group via torch.distributed — the cross-process
fallback over sockets/DCN (ref: python/ray/util/collective/collective_group/
torch_gloo_collective_group.py).  Rendezvous of the TCP store rides the GCS
KV instead of a named store actor."""

from __future__ import annotations

import logging
import time

import numpy as np

from ant_ray_tpu.util.collective import types
from ant_ray_tpu.util.collective.collective_group.base import BaseGroup

logger = logging.getLogger(__name__)

_REDUCE_MAP = None


def _dist():
    import torch.distributed as dist  # noqa: PLC0415

    global _REDUCE_MAP
    if _REDUCE_MAP is None:
        _REDUCE_MAP = {
            types.ReduceOp.SUM: dist.ReduceOp.SUM,
            types.ReduceOp.PRODUCT: dist.ReduceOp.PRODUCT,
            types.ReduceOp.MIN: dist.ReduceOp.MIN,
            types.ReduceOp.MAX: dist.ReduceOp.MAX,
            types.ReduceOp.AVERAGE: dist.ReduceOp.AVG,
        }
    return dist


class GlooGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str,
                 init_method: str):
        super().__init__(world_size, rank, group_name)
        dist = _dist()
        if dist.is_initialized():
            raise RuntimeError(
                "torch.distributed already initialized in this process; "
                "only one live gloo group per process is supported "
                "(destroy the existing group first)")
        dist.init_process_group(
            "gloo", init_method=init_method, rank=rank,
            world_size=world_size)

    @classmethod
    def backend(cls):
        return "gloo"

    def destroy_group(self):
        dist = _dist()
        if dist.is_initialized():
            dist.destroy_process_group()

    # ---- torch/jax/numpy interop

    @staticmethod
    def _to_torch(tensor):
        import torch  # noqa: PLC0415

        if isinstance(tensor, torch.Tensor):
            return tensor, ("torch", None)
        arr = np.asarray(tensor)
        origin = type(tensor).__module__
        try:
            return torch.from_numpy(arr.copy()), (origin, None)
        except TypeError:
            # Narrow floats the numpy↔torch bridge rejects (ml_dtypes
            # bfloat16): reduce at float32, restore dtype on the way out.
            return (torch.from_numpy(arr.astype(np.float32)),
                    (origin, arr.dtype))

    @staticmethod
    def _from_torch(t, origin):
        module, cast = origin if isinstance(origin, tuple) else (origin,
                                                                 None)
        if module == "torch":
            return t
        out = t.numpy()
        if cast is not None:
            out = out.astype(cast)
        if module.startswith("jax"):
            import jax.numpy as jnp  # noqa: PLC0415

            return jnp.asarray(out)
        return out

    # ---- verbs

    def allreduce(self, tensors, opts: types.AllReduceOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.all_reduce(t, op=_REDUCE_MAP[opts.reduce_op])
        return [self._from_torch(t, origin)]

    def allreduce_coalesced(self, tensors,
                            opts: types.AllReduceCoalescedOptions):
        """Fused path: one flat torch tensor (and one ``all_reduce``)
        per dtype-segregated bucket instead of a per-tensor loop — the
        per-call gloo round trip is paid ~#buckets times, not #tensors
        times.  A reduced-precision bucket (``transport_dtype``) was
        quantized once at pack time; the reduction itself runs at
        float32 (accumulate-in-f32, EQuARX-style)."""
        import torch  # noqa: PLC0415

        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        dist = _dist()
        if getattr(self, "_fusion_stats", None) is None:
            self._fusion_stats = fusion.FusionStats()

        def transfer(flat, bucket):
            if bucket.transport_dtype != bucket.dtype:
                # The lossy cast already happened in pack_bucket;
                # upcast so gloo accumulates at full precision.
                flat = flat.astype(np.float32)
            try:
                return torch.from_numpy(flat)   # zero-copy wrap
            except TypeError:
                # ml_dtypes bucket (bfloat16 leaves): float32 bridge —
                # unpack restores the leaf dtype.
                return torch.from_numpy(flat.astype(np.float32))

        def reduce_bucket(t, bucket):
            dist.all_reduce(t, op=_REDUCE_MAP[opts.reduce_op])
            return t.numpy()

        return fusion.run_coalesced(tensors, opts, transfer_fn=transfer,
                                    collective_fn=reduce_bucket,
                                    stats=self._fusion_stats)

    def barrier(self, opts: types.BarrierOptions):
        _dist().barrier()

    def reduce(self, tensors, opts: types.ReduceOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.reduce(t, dst=opts.root_rank, op=_REDUCE_MAP[opts.reduce_op])
        return [self._from_torch(t, origin)]

    def broadcast(self, tensors, opts: types.BroadcastOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.broadcast(t, src=opts.root_rank)
        return [self._from_torch(t, origin)]

    def allgather(self, tensors, opts: types.AllGatherOptions):
        import torch  # noqa: PLC0415

        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        out = [torch.empty_like(t) for _ in range(self._world_size)]
        dist.all_gather(out, t)
        return [[self._from_torch(o, origin) for o in out]]

    def reducescatter(self, tensors, opts: types.ReduceScatterOptions):
        import torch  # noqa: PLC0415

        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        if t.shape[0] % self._world_size != 0:
            raise ValueError("reducescatter needs dim0 divisible by world")
        dist.all_reduce(t, op=_REDUCE_MAP[opts.reduce_op])
        chunk = t.shape[0] // self._world_size
        piece = t[self._rank * chunk:(self._rank + 1) * chunk]
        return [self._from_torch(piece, origin)]

    def send(self, tensors, opts: types.SendOptions):
        dist = _dist()
        t, _origin = self._to_torch(tensors[0])
        dist.send(t, dst=opts.dst_rank)

    def recv(self, tensors, opts: types.RecvOptions):
        dist = _dist()
        t, origin = self._to_torch(tensors[0])
        dist.recv(t, src=opts.src_rank)
        return [self._from_torch(t, origin)]


def rendezvous_init_method(group_name: str, rank: int,
                           timeout_s: float = 60.0) -> str:
    """Agree on a TCP init method via GCS KV (replaces the reference's
    named-actor NCCLUniqueID rendezvous, nccl_collective_group.py:29-78)."""
    from ant_ray_tpu._private.protocol import find_free_port  # noqa: PLC0415
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    key = f"collective:{group_name}:init_method"
    if rank == 0:
        method = f"tcp://127.0.0.1:{find_free_port()}"
        runtime._gcs.call("KVPut", {"key": key, "value": method.encode()})
        return method
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = runtime._gcs.call("KVGet", {"key": key})
        if value is not None:
            return value.decode()
        time.sleep(0.05)
    raise TimeoutError(f"rendezvous for group {group_name!r} timed out")
