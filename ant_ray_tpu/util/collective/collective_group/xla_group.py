"""XLA collective group — the TPU-native replacement for the reference's
NCCL group (ref: python/ray/util/collective/collective_group/
nccl_collective_group.py, 836 LoC of cupy/NCCL machinery).

Design: collectives lower to XLA collective ops (psum / all_gather /
psum_scatter) over a ``jax.sharding.Mesh``, executed as cached jitted
``shard_map`` programs, so repeated calls hit the XLA executable cache and
ride ICI inside a slice (DCN across slices when the group is federated via
jax.distributed).  Two membership modes share one code path:

* **in-process** (jax.process_count() == 1): group members are this
  process's devices — the natural single-controller TPU mode.  The
  ``*_multidevice`` verbs (parity with the reference's ``*_multigpu``) run
  real multi-device collectives over the local mesh; the per-rank verbs
  degenerate to world_size == 1.
* **federated** (multi-host): each member process contributes devices to a
  global mesh; the jax.distributed coordinator rendezvous rides the GCS KV
  (replacing the named-actor NCCLUniqueID store,
  nccl_collective_group.py:29-78).

Block protocol: per-member tensors of shape S are stacked into a global
array of shape (n, *S) sharded one block per device; kernels see (1, *S)
blocks and return (k, *S') blocks that concatenate over the mesh axis.
"""

from __future__ import annotations

import functools
import logging

import numpy as np

from ant_ray_tpu.util.collective import types
from ant_ray_tpu.util.collective.collective_group.base import BaseGroup

logger = logging.getLogger(__name__)


def _jax():
    from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

    return import_jax()


def _shard_map():
    from ant_ray_tpu._private.jax_utils import shard_map  # noqa: PLC0415

    return shard_map()


class XLAGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str,
                 devices=None):
        super().__init__(world_size, rank, group_name)
        jax = _jax()
        if world_size > 1 and jax.process_count() < world_size:
            raise RuntimeError(
                f"xla group {group_name!r} needs {world_size} federated "
                f"processes but jax.process_count() == {jax.process_count()}."
                " Initialize jax.distributed before creating multi-host "
                "groups.")
        self._devices = (list(devices) if devices is not None
                         else list(jax.devices()))
        # One representative device per member process for per-rank verbs.
        by_proc: dict[int, list] = {}
        for d in self._devices:
            by_proc.setdefault(d.process_index, []).append(d)
        self._rank_devices = [
            sorted(devs, key=lambda d: d.id)[0]
            for _proc, devs in sorted(by_proc.items())
        ]
        self._local_devices = [d for d in self._devices
                               if d.process_index == jax.process_index()]

    @classmethod
    def backend(cls):
        return "xla"

    @property
    def local_device_count(self) -> int:
        return len(self._local_devices)

    # ------------------------------------------------------------ compile

    @functools.lru_cache(maxsize=256)  # noqa: B019 — cache dies with group
    def _compiled(self, verb: str, shape: tuple, dtype: str, n_dev: int,
                  extra):
        jax = _jax()
        from jax.sharding import Mesh, NamedSharding  # noqa: PLC0415
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        devices = (self._rank_devices if n_dev == len(self._rank_devices)
                   else self._devices)
        mesh = Mesh(np.array(devices[:n_dev]), ("world",))
        axis = "world"

        def op(x):
            # x: this device's block, shape (1, *S)
            if verb == "allreduce_sum":
                return jax.lax.psum(x, axis)
            if verb == "allreduce_min":
                return jax.lax.pmin(x, axis)
            if verb == "allreduce_max":
                return jax.lax.pmax(x, axis)
            if verb == "allreduce_average":
                return jax.lax.pmean(x, axis)
            if verb == "broadcast":
                return jax.lax.all_gather(x[0], axis)[extra][None]
            if verb == "allgather":
                # out block: (n, *S) — every device gets the full gather
                return jax.lax.all_gather(x[0], axis)
            if verb == "reducescatter_sum":
                # x[0]: (d0, *rest) with d0 % n == 0 → (d0/n, *rest)
                return jax.lax.psum_scatter(x[0], axis, tiled=True)
            raise ValueError(verb)

        fn = _shard_map()(op, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
        return jax.jit(fn), mesh, NamedSharding(mesh, P(axis))

    # ------------------------------------------------------------ runners

    def _run_multidevice(self, verb: str, tensors: list, extra=None) -> list:
        """tensors: one per local device → list of per-device out blocks."""
        jax = _jax()
        n = len(tensors)
        if n != len(self._local_devices):
            raise ValueError(
                f"expected one tensor per local device "
                f"({len(self._local_devices)}), got {n}")
        t0 = np.asarray(tensors[0])
        jitted, mesh, sharding = self._compiled(
            verb, tuple(t0.shape), str(t0.dtype), len(self._devices), extra)
        mesh_devices = list(mesh.devices.flat)
        local_order = [d for d in mesh_devices if d in self._local_devices]
        shards = [
            jax.device_put(np.asarray(t)[None], d)
            for t, d in zip(tensors, local_order)
        ]
        global_shape = (len(self._devices),) + tuple(t0.shape)
        arr = jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)
        out = jitted(arr)
        by_device = {s.device: s.data for s in out.addressable_shards}
        return [by_device[d] for d in local_order]

    def _run_rank_verb(self, verb: str, tensor, extra=None):
        """One tensor per member process; returns this rank's out block."""
        jax = _jax()
        t = np.asarray(tensor)
        jitted, mesh, sharding = self._compiled(
            verb, tuple(t.shape), str(t.dtype), len(self._rank_devices),
            extra)
        shard = jax.device_put(t[None], self._rank_devices[self._rank])
        arr = jax.make_array_from_single_device_arrays(
            (self._world_size,) + t.shape, sharding, [shard])
        return jitted(arr).addressable_shards[0].data

    _REDUCE_VERBS = {
        types.ReduceOp.SUM: "allreduce_sum",
        types.ReduceOp.MIN: "allreduce_min",
        types.ReduceOp.MAX: "allreduce_max",
        types.ReduceOp.AVERAGE: "allreduce_average",
    }

    def _reduce_verb(self, op: types.ReduceOp) -> str:
        verb = self._REDUCE_VERBS.get(op)
        if verb is None:
            raise NotImplementedError(
                f"{op} is not supported by the xla backend; allgather and "
                "reduce locally instead")
        return verb

    # ------------------------------------------------------------ verbs

    def allreduce(self, tensors, opts: types.AllReduceOptions):
        if self._world_size == 1:
            return [tensors[0]]
        block = self._run_rank_verb(self._reduce_verb(opts.reduce_op),
                                    tensors[0])
        return [block[0]]

    def barrier(self, opts: types.BarrierOptions):
        if self._world_size > 1:
            self._run_rank_verb("allreduce_sum", np.zeros((1,), np.float32))

    def reduce(self, tensors, opts: types.ReduceOptions):
        # SPMD collectives give everyone the reduction; a superset of the
        # reference's "result lands on root_rank" contract.
        return self.allreduce(
            tensors, types.AllReduceOptions(reduce_op=opts.reduce_op))

    def broadcast(self, tensors, opts: types.BroadcastOptions):
        if self._world_size == 1:
            return [tensors[0]]
        block = self._run_rank_verb("broadcast", tensors[0],
                                    extra=opts.root_rank)
        return [block[0]]

    def allgather(self, tensors, opts: types.AllGatherOptions):
        if self._world_size == 1:
            return [[tensors[0]]]
        block = self._run_rank_verb("allgather", tensors[0])
        return [[block[i] for i in range(self._world_size)]]

    def reducescatter(self, tensors, opts: types.ReduceScatterOptions):
        if opts.reduce_op != types.ReduceOp.SUM:
            raise NotImplementedError("reducescatter supports SUM only")
        if self._world_size == 1:
            return [tensors[0]]
        block = self._run_rank_verb("reducescatter_sum", tensors[0])
        return [block]

    # ---- multi-device variants (parity: reference *_multigpu verbs)

    def allreduce_multidevice(self, tensors: list,
                              opts: types.AllReduceOptions):
        blocks = self._run_multidevice(self._reduce_verb(opts.reduce_op),
                                       tensors)
        return [b[0] for b in blocks]

    def broadcast_multidevice(self, tensors: list,
                              opts: types.BroadcastOptions):
        blocks = self._run_multidevice("broadcast", tensors,
                                       extra=opts.root_rank)
        return [b[0] for b in blocks]

    def allgather_multidevice(self, tensors: list,
                              opts: types.AllGatherOptions):
        blocks = self._run_multidevice("allgather", tensors)
        return [[b[i] for i in range(len(self._devices))] for b in blocks]

    def reducescatter_multidevice(self, tensors: list,
                                  opts: types.ReduceScatterOptions):
        if opts.reduce_op != types.ReduceOp.SUM:
            raise NotImplementedError("reducescatter supports SUM only")
        return self._run_multidevice("reducescatter_sum", tensors)

    # ---- p2p

    def send(self, tensors, opts: types.SendOptions):
        raise NotImplementedError(
            "xla-backend host-level send/recv goes through the object "
            "plane; ICI p2p lives in compiled step-graph channels")

    def recv(self, tensors, opts: types.RecvOptions):
        raise NotImplementedError(
            "xla-backend host-level send/recv goes through the object "
            "plane; ICI p2p lives in compiled step-graph channels")

    def destroy_group(self):
        self._compiled.cache_clear()
