"""XLA collective group — the TPU-native replacement for the reference's
NCCL group (ref: python/ray/util/collective/collective_group/
nccl_collective_group.py, 836 LoC of cupy/NCCL machinery).

Design: collectives lower to XLA collective ops (psum / all_gather /
psum_scatter) over a ``jax.sharding.Mesh``, executed as cached jitted
``shard_map`` programs, so repeated calls hit the XLA executable cache and
ride ICI inside a slice (DCN across slices when the group is federated via
jax.distributed).  Two membership modes share one code path:

* **in-process** (jax.process_count() == 1): group members are this
  process's devices — the natural single-controller TPU mode.  The
  ``*_multidevice`` verbs (parity with the reference's ``*_multigpu``) run
  real multi-device collectives over the local mesh; the per-rank verbs
  degenerate to world_size == 1.
* **federated** (multi-host): each member process contributes devices to a
  global mesh; the jax.distributed coordinator rendezvous rides the GCS KV
  (replacing the named-actor NCCLUniqueID store,
  nccl_collective_group.py:29-78).

Block protocol: per-member tensors of shape S are stacked into a global
array of shape (n, *S) sharded one block per device; kernels see (1, *S)
blocks and return (k, *S') blocks that concatenate over the mesh axis.
"""

from __future__ import annotations

import functools
import logging

import numpy as np

from ant_ray_tpu.util.collective import types
from ant_ray_tpu.util.collective.collective_group.base import BaseGroup

logger = logging.getLogger(__name__)


def _jax():
    from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

    return import_jax()


def _shard_map():
    from ant_ray_tpu._private.jax_utils import shard_map  # noqa: PLC0415

    return shard_map()


class XLAGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str,
                 devices=None):
        super().__init__(world_size, rank, group_name)
        jax = _jax()
        # Mesh-based verbs need one process per rank (jax.distributed);
        # the KV-mailbox p2p verbs (send/recv) work without it, so the
        # check is deferred to the verbs that actually need the mesh.
        self._federated_ok = (world_size <= 1
                              or jax.process_count() >= world_size)
        self._devices = (list(devices) if devices is not None
                         else list(jax.devices()))
        # One representative device per member process for per-rank verbs.
        by_proc: dict[int, list] = {}
        for d in self._devices:
            by_proc.setdefault(d.process_index, []).append(d)
        self._rank_devices = [
            sorted(devs, key=lambda d: d.id)[0]
            for _proc, devs in sorted(by_proc.items())
        ]
        self._local_devices = [d for d in self._devices
                               if d.process_index == jax.process_index()]

    @classmethod
    def backend(cls):
        return "xla"

    @property
    def local_device_count(self) -> int:
        return len(self._local_devices)

    # ------------------------------------------------------------ compile

    @functools.lru_cache(maxsize=256)  # noqa: B019 — cache dies with group
    def _compiled(self, verb: str, shape: tuple, dtype: str, n_dev: int,
                  extra):
        jax = _jax()
        from jax.sharding import Mesh, NamedSharding  # noqa: PLC0415
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        devices = (self._rank_devices if n_dev == len(self._rank_devices)
                   else self._devices)
        if verb.startswith("hier_"):
            return self._compile_hierarchical(verb, shape, n_dev, extra,
                                              devices)
        if verb.endswith("_q8"):
            return self._compile_q8(verb, shape, n_dev, extra, devices)
        mesh = Mesh(np.array(devices[:n_dev]), ("world",))
        axis = "world"

        def op(x):
            # x: this device's block, shape (1, *S)
            if verb.endswith("_accf32"):
                # Reduced-precision transport bucket (fusion.py): the
                # operand arrived in the narrow wire dtype; accumulate
                # at float32 (EQuARX-style) and return float32 — the
                # unpack stage restores the leaf dtype.
                import jax.numpy as jnp  # noqa: PLC0415

                return op_base(verb[:-len("_accf32")],
                               x.astype(jnp.float32))
            return op_base(verb, x)

        def op_base(verb, x):
            if verb == "allreduce_sum":
                return jax.lax.psum(x, axis)
            if verb == "allreduce_min":
                return jax.lax.pmin(x, axis)
            if verb == "allreduce_max":
                return jax.lax.pmax(x, axis)
            if verb == "allreduce_average":
                return jax.lax.pmean(x, axis)
            if verb == "broadcast":
                return jax.lax.all_gather(x[0], axis)[extra][None]
            if verb == "allgather":
                # out block: (n, *S) — every device gets the full gather
                return jax.lax.all_gather(x[0], axis)
            if verb == "reducescatter_sum":
                # x[0]: (d0, *rest) with d0 % n == 0 → (d0/n, *rest)
                return jax.lax.psum_scatter(x[0], axis, tiled=True)
            if verb.startswith("reducescatter_"):
                # MIN/MAX/AVERAGE: no fused XLA op — gather, reduce
                # locally, keep this rank's tile.
                g = jax.lax.all_gather(x[0], axis)   # (n, d0, *rest)
                red = {"min": g.min(axis=0), "max": g.max(axis=0),
                       "average": g.mean(axis=0)}[verb.split("_", 1)[1]]
                tile = red.shape[0] // n_dev
                index = jax.lax.axis_index(axis)
                return jax.lax.dynamic_slice_in_dim(
                    red, index * tile, tile, axis=0)
            raise ValueError(verb)

        fn = _shard_map()(op, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
        return jax.jit(fn), mesh, NamedSharding(mesh, P(axis))

    def _compile_q8(self, verb: str, shape: tuple, n_dev: int, extra,
                    devices):
        """Blockwise-int8 quantized allreduce (EQuARX-style): the
        all_gather moves int8 codes plus the float32 scale sidecar —
        the only bytes on the wire — and every rank dequantizes and
        accumulates at float32.  ``verb`` is ``allreduce_{sum,average}_q8``,
        ``extra`` is ``(block, n_blocks)``; one compiled program per
        bucket shape rides the same ``_compiled`` LRU as the plain
        verbs."""
        jax = _jax()
        import jax.numpy as jnp  # noqa: PLC0415
        from jax.sharding import Mesh, NamedSharding  # noqa: PLC0415
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        block, _n_blocks = extra
        size = shape[0]
        average = verb.startswith("allreduce_average")
        mesh = Mesh(np.array(devices[:n_dev]), ("world",))
        axis = "world"

        def op(q, s):
            # q: (1, size) int8, s: (1, n_blocks) float32
            qg = jax.lax.all_gather(q[0], axis)      # (n, size) — wire
            sg = jax.lax.all_gather(s[0], axis)      # (n, n_blocks)
            scale = jnp.repeat(sg, block, axis=1)[:, :size]
            out = (qg.astype(jnp.float32) * scale).sum(axis=0)
            if average:
                out = out / n_dev
            return out[None]

        fn = _shard_map()(op, mesh=mesh, in_specs=(P(axis), P(axis)),
                          out_specs=P(axis))
        return jax.jit(fn), mesh, NamedSharding(mesh, P(axis))

    def _compile_hierarchical(self, verb: str, shape: tuple, n_dev: int,
                              extra, devices):
        """Two-level allreduce over a (slice, intra) mesh: reduce-
        scatter within each slice (ICI), psum across slices (the DCN
        exchange — each chunk crosses slice boundaries ONCE per slice,
        so cross-slice traffic scales with num_slices, not world size),
        then all_gather within the slice to rebuild the bucket.
        ``verb`` is ``hier_allreduce_{sum,average}[_accf32]``; ``extra``
        is the SliceTopology's rank partition (must be the regular
        contiguous layout matching device order)."""
        jax = _jax()
        import jax.numpy as jnp  # noqa: PLC0415
        from jax.sharding import Mesh, NamedSharding  # noqa: PLC0415
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        slices = extra
        num_slices = len(slices)
        per = n_dev // num_slices
        size = shape[0]
        accf32 = verb.endswith("_accf32")
        average = "allreduce_average" in verb
        mesh = Mesh(np.array(devices[:n_dev]).reshape(num_slices, per),
                    ("slice", "intra"))

        def op(x):
            y = x[0, 0]                               # (size,)
            if accf32:
                y = y.astype(jnp.float32)
            if per > 1 and size % per == 0:
                y = jax.lax.psum_scatter(y, "intra", tiled=True)
                y = jax.lax.psum(y, "slice")
                y = jax.lax.all_gather(y, "intra", tiled=True)
            else:
                # Odd-sized bucket: no clean scatter tiling — reduce
                # whole within the slice, then across slices.
                y = jax.lax.psum(y, "intra")
                y = jax.lax.psum(y, "slice")
            if average:
                y = y / n_dev
            return y[None, None]

        spec = P("slice", "intra")
        fn = _shard_map()(op, mesh=mesh, in_specs=spec, out_specs=spec)
        return jax.jit(fn), mesh, NamedSharding(mesh, spec)

    # ------------------------------------------------------------ runners

    def _run_multidevice(self, verb: str, tensors: list, extra=None) -> list:
        """tensors: one per local device → list of per-device out blocks."""
        jax = _jax()
        n = len(tensors)
        if n != len(self._local_devices):
            raise ValueError(
                f"expected one tensor per local device "
                f"({len(self._local_devices)}), got {n}")
        t0 = np.asarray(tensors[0])
        jitted, mesh, sharding = self._compiled(
            verb, tuple(t0.shape), str(t0.dtype), len(self._devices), extra)
        mesh_devices = list(mesh.devices.flat)
        local_order = [d for d in mesh_devices if d in self._local_devices]
        shards = [
            jax.device_put(np.asarray(t)[None], d)
            for t, d in zip(tensors, local_order)
        ]
        global_shape = (len(self._devices),) + tuple(t0.shape)
        arr = jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)
        out = jitted(arr)
        by_device = {s.device: s.data for s in out.addressable_shards}
        return [by_device[d] for d in local_order]

    def _stage_rank_verb(self, verb: str, tensor, extra=None):
        """Transfer stage of a per-rank verb: compile-cache lookup plus
        async-dispatched host→device ``device_put``.  Split from the
        execute stage so the fused coalesced path can issue bucket
        k+1's transfer while bucket k's collective runs."""
        jax = _jax()
        if not self._federated_ok:
            raise RuntimeError(
                f"xla group {self._group_name!r} needs "
                f"{self._world_size} federated processes but "
                f"jax.process_count() == {jax.process_count()}. "
                "Initialize jax.distributed before using mesh "
                "collectives (send/recv work without it).")
        t = np.asarray(tensor)
        jitted, mesh, sharding = self._compiled(
            verb, tuple(t.shape), str(t.dtype), len(self._rank_devices),
            extra)
        shard = jax.device_put(t[None], self._rank_devices[self._rank])
        arr = jax.make_array_from_single_device_arrays(
            (self._world_size,) + t.shape, sharding, [shard])
        return jitted, arr

    def _run_rank_verb(self, verb: str, tensor, extra=None):
        """One tensor per member process; returns this rank's out block."""
        jitted, arr = self._stage_rank_verb(verb, tensor, extra)
        return jitted(arr).addressable_shards[0].data

    _REDUCE_VERBS = {
        types.ReduceOp.SUM: "allreduce_sum",
        types.ReduceOp.MIN: "allreduce_min",
        types.ReduceOp.MAX: "allreduce_max",
        types.ReduceOp.AVERAGE: "allreduce_average",
    }

    def _reduce_verb(self, op: types.ReduceOp) -> str:
        verb = self._REDUCE_VERBS.get(op)
        if verb is None:
            raise NotImplementedError(
                f"{op} is not supported by the xla backend; allgather and "
                "reduce locally instead")
        return verb

    # ------------------------------------------------------------ verbs

    def allreduce(self, tensors, opts: types.AllReduceOptions):
        if self._world_size == 1:
            return [tensors[0]]
        block = self._run_rank_verb(self._reduce_verb(opts.reduce_op),
                                    tensors[0])
        return [block[0]]

    def allreduce_coalesced(self, tensors,
                            opts: types.AllReduceCoalescedOptions):
        """Fused path: one compiled shard_map collective per *bucket*
        shape (reusing the ``_compiled`` LRU) instead of one per
        tensor, with bucket k+1's host→HBM transfer pipelined against
        bucket k's collective.  Runs the compiled program even at
        world_size == 1 (psum over a 1-device mesh is identity) so the
        bucketed compile-cache behavior is identical at any scale."""
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        if getattr(self, "_fusion_stats", None) is None:
            self._fusion_stats = fusion.FusionStats()

        def transfer(flat, bucket):
            return self.bucket_transfer(flat, bucket, opts)

        def reduce_bucket(staged, bucket):
            return self.bucket_reduce(staged, bucket, opts)

        return fusion.run_coalesced(tensors, opts, transfer_fn=transfer,
                                    collective_fn=reduce_bucket,
                                    stats=self._fusion_stats)

    # ---- per-bucket stages (driven by run_coalesced AND GradientSyncer)

    def _hier_topology(self, opts):
        """The validated hierarchy for this group, or None.  The xla
        mesh reshape needs the regular contiguous rank→slice layout
        (rank i on mesh cell (i // per, i % per)); anything else falls
        back to the flat ring."""
        from ant_ray_tpu.util.collective.types import SliceTopology  # noqa: PLC0415

        topo = getattr(opts, "hierarchy", None)
        if topo is None:
            return None
        world = self._world_size
        if world % max(1, topo.num_slices) != 0:
            return None
        if topo.slices != SliceTopology.regular(
                world, topo.num_slices).slices:
            return None
        return topo

    def bucket_transfer(self, flat, bucket,
                        opts: types.AllReduceCoalescedOptions):
        """Transfer stage of one fused bucket: compile-cache lookup +
        host→HBM ``device_put``.  Picks the wire program — plain,
        ``_accf32`` (narrow-float transport, f32 accumulate), ``_q8``
        (blockwise int8 + scale sidecar), or ``hier_*`` (two-level
        slice schedule; quantized buckets keep the flat q8 exchange)."""
        jax = _jax()
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        verb = self._reduce_verb(opts.reduce_op)
        if bucket.transport_dtype == "int8":
            q, scales = flat
            jitted, arr_q = self._stage_rank_operand(
                verb + "_q8", q,
                key_shape=tuple(q.shape),
                key_dtype="int8",
                extra=(fusion.QUANT_BLOCK,
                       fusion.quant_blocks(bucket.size)))
            _jit2, arr_s = self._stage_rank_operand(
                verb + "_q8", scales,
                key_shape=tuple(q.shape), key_dtype="int8",
                extra=(fusion.QUANT_BLOCK,
                       fusion.quant_blocks(bucket.size)),
                operand_index=1)
            return ("q8", jitted, (arr_q, arr_s), self._world_size)
        topo = self._hier_topology(opts)
        if topo is not None:
            t = np.asarray(flat)
            wire_verb = "hier_" + verb + (
                "_accf32" if bucket.transport_dtype != bucket.dtype
                else "")
            jitted, mesh, sharding = self._compiled(
                wire_verb, tuple(t.shape), str(t.dtype),
                len(self._rank_devices), topo.slices)
            per = self._world_size // topo.num_slices
            shard = jax.device_put(t[None, None],
                                   self._rank_devices[self._rank])
            arr = jax.make_array_from_single_device_arrays(
                (topo.num_slices, per) + t.shape, sharding, [shard])
            return ("hier", jitted, (arr,), topo.num_slices)
        wire_verb = verb + ("_accf32"
                            if bucket.transport_dtype != bucket.dtype
                            else "")
        jitted, arr = self._stage_rank_verb(wire_verb, flat)
        return ("flat", jitted, (arr,), self._world_size)

    def _stage_rank_operand(self, verb: str, tensor, *, key_shape,
                            key_dtype, extra, operand_index: int = 0):
        """Stage one operand of a (possibly multi-input) compiled verb:
        the LRU key is pinned to the BUCKET's shape/dtype so sidecar
        operands (q8 scales) do not mint extra cache entries."""
        jax = _jax()
        if not self._federated_ok:
            raise RuntimeError(
                f"xla group {self._group_name!r} needs "
                f"{self._world_size} federated processes but "
                f"jax.process_count() == {jax.process_count()}.")
        t = np.asarray(tensor)
        jitted, mesh, sharding = self._compiled(
            verb, key_shape, key_dtype, len(self._rank_devices), extra)
        shard = jax.device_put(t[None], self._rank_devices[self._rank])
        arr = jax.make_array_from_single_device_arrays(
            (self._world_size,) + t.shape, sharding, [shard])
        return jitted, arr

    def bucket_reduce(self, staged, bucket,
                      opts: types.AllReduceCoalescedOptions):
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        if getattr(self, "_fusion_stats", None) is None:
            self._fusion_stats = fusion.FusionStats()
        kind, jitted, args, dcn = staged
        out = jitted(*args)
        block = out.addressable_shards[0].data
        self._fusion_stats.dcn_participants += dcn
        if kind == "hier":
            return block[0, 0]
        return block[0]

    def barrier(self, opts: types.BarrierOptions):
        if self._world_size > 1:
            self._run_rank_verb("allreduce_sum", np.zeros((1,), np.float32))

    def reduce(self, tensors, opts: types.ReduceOptions):
        # The SPMD collective gives every rank the reduction; the
        # reference contract is "result lands on root_rank, other
        # buffers untouched" — so non-roots hand back their input.
        reduced = self.allreduce(
            tensors, types.AllReduceOptions(reduce_op=opts.reduce_op))
        if self._world_size > 1 and self._rank != opts.root_rank:
            return [tensors[0]]
        return reduced

    def broadcast(self, tensors, opts: types.BroadcastOptions):
        if self._world_size == 1:
            return [tensors[0]]
        block = self._run_rank_verb("broadcast", tensors[0],
                                    extra=opts.root_rank)
        return [block[0]]

    def allgather(self, tensors, opts: types.AllGatherOptions):
        if self._world_size == 1:
            return [[tensors[0]]]
        block = self._run_rank_verb("allgather", tensors[0])
        return [[block[i] for i in range(self._world_size)]]

    _SCATTER_VERBS = {
        types.ReduceOp.SUM: "reducescatter_sum",
        types.ReduceOp.MIN: "reducescatter_min",
        types.ReduceOp.MAX: "reducescatter_max",
        types.ReduceOp.AVERAGE: "reducescatter_average",
    }

    def _scatter_verb(self, op: types.ReduceOp, tensor, n: int) -> str:
        verb = self._SCATTER_VERBS.get(op)
        if verb is None:
            raise NotImplementedError(
                f"{op} is not supported by xla reducescatter")
        d0 = np.asarray(tensor).shape[0]
        if d0 % n != 0:
            raise ValueError(
                f"reducescatter leading dim {d0} not divisible by "
                f"group size {n}")
        return verb

    def reducescatter(self, tensors, opts: types.ReduceScatterOptions):
        if self._world_size == 1:
            return [tensors[0]]
        verb = self._scatter_verb(opts.reduce_op, tensors[0],
                                  self._world_size)
        block = self._run_rank_verb(verb, tensors[0])
        return [block]

    # ---- multi-device variants (parity: reference *_multigpu verbs)

    def allreduce_multidevice(self, tensors: list,
                              opts: types.AllReduceOptions):
        blocks = self._run_multidevice(self._reduce_verb(opts.reduce_op),
                                       tensors)
        return [b[0] for b in blocks]

    def broadcast_multidevice(self, tensors: list,
                              opts: types.BroadcastOptions):
        blocks = self._run_multidevice("broadcast", tensors,
                                       extra=opts.root_rank)
        return [b[0] for b in blocks]

    def allgather_multidevice(self, tensors: list,
                              opts: types.AllGatherOptions):
        blocks = self._run_multidevice("allgather", tensors)
        return [[b[i] for i in range(len(self._devices))] for b in blocks]

    def reducescatter_multidevice(self, tensors: list,
                                  opts: types.ReduceScatterOptions):
        verb = self._scatter_verb(opts.reduce_op, tensors[0],
                                  len(self._devices))
        return self._run_multidevice(verb, tensors)

    # ---- p2p
    # Host-level point-to-point rides the control plane through GCS KV
    # mailboxes (ICI p2p belongs to compiled step-graph channels / the
    # ppermute inside sharded programs).  Each (src → dst) pair keeps a
    # sequence so repeated sends pair with recvs in order, matching the
    # reference's NCCL send/recv contract
    # (ref: collective.py:601,664).

    def _mailbox_key(self, src: int, dst: int, seq: int) -> str:
        return (f"collective_p2p:{self._group_name}:"
                f"{src}->{dst}:{seq}")

    def send(self, tensors, opts: types.SendOptions):
        import pickle  # noqa: PLC0415
        import time as _time  # noqa: PLC0415

        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        seq_attr = f"_send_seq_{opts.dst_rank}"
        attempt_attr = f"_send_attempt_{opts.dst_rank}"
        seq = getattr(self, seq_attr, 0)
        attempt = getattr(self, attempt_attr, 0)
        key = self._mailbox_key(self._rank, opts.dst_rank, seq) \
            + f"#a{attempt}"
        blob = pickle.dumps(np.asarray(tensors[0]), protocol=5)
        gcs = global_worker.runtime._gcs
        # Exchange protocol (retry-safe): the outcome of each
        # (seq, attempt) is decided exactly once by a put-if-absent race
        # on an arbitration key — "delivered" (receiver claims after
        # reading the blob) vs "withdrawn" (sender claims at its
        # deadline).  Every operation either is idempotent (KVGet,
        # re-KVPut of the same value) or resolves ambiguity by
        # re-reading the arbitration key, so an RPC connection retry can
        # never lose a message or desync the pair.  A withdrawn attempt
        # stays decided (deciding it twice is what reintroduces the
        # race); both sides move to attempt+1, so an application retry
        # of a timed-out send starts fresh.  Keys two sequences back are
        # garbage-collected here — by the time seq N+2 is sent, the
        # receiver has fully finished seq N.
        arb = key + ":arb"
        if seq >= 2:
            prefix = self._mailbox_key(self._rank, opts.dst_rank, seq - 2)
            for stale in gcs.call("KVKeys", {"prefix": prefix},
                                  retries=3) or []:
                gcs.call("KVDel", {"key": stale}, retries=3)
        gcs.call("KVPut", {"key": key, "value": blob}, retries=3)
        deadline = _time.monotonic() + opts.timeout_ms / 1000.0
        poll = 0.002
        while _time.monotonic() < deadline:
            if gcs.call("KVGet", {"key": arb}, retries=3) == b"delivered":
                setattr(self, seq_attr, seq + 1)
                setattr(self, attempt_attr, 0)
                return
            _time.sleep(poll)
            poll = min(poll * 2, 0.05)  # backoff: bounded GCS RPC rate
        gcs.call("KVPut", {"key": arb, "value": b"withdrawn",
                           "overwrite": False}, retries=3)
        if gcs.call("KVGet", {"key": arb}, retries=3) == b"delivered":
            setattr(self, seq_attr, seq + 1)  # receiver won at the wire
            setattr(self, attempt_attr, 0)
            return
        setattr(self, attempt_attr, attempt + 1)  # retry starts fresh
        raise TimeoutError(
            f"send to rank {opts.dst_rank} not consumed in time")

    def recv(self, tensors, opts: types.RecvOptions):
        import pickle  # noqa: PLC0415
        import time as _time  # noqa: PLC0415

        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        seq_attr = f"_recv_seq_{opts.src_rank}"
        attempt_attr = f"_recv_attempt_{opts.src_rank}"
        seq = getattr(self, seq_attr, 0)
        attempt = getattr(self, attempt_attr, 0)
        gcs = global_worker.runtime._gcs
        deadline = _time.monotonic() + opts.timeout_ms / 1000.0
        poll = 0.002
        while _time.monotonic() < deadline:
            key = self._mailbox_key(opts.src_rank, self._rank, seq) \
                + f"#a{attempt}"
            arb = key + ":arb"
            blob = gcs.call("KVGet", {"key": key}, retries=3)
            if blob is not None:
                # Claim delivery via put-if-absent on the arbitration
                # key; on a lost reply the re-read below resolves who
                # won (see the protocol note in send()).
                won = gcs.call("KVPut", {"key": arb, "value": b"delivered",
                                         "overwrite": False}, retries=3)
                verdict = (b"delivered" if won else
                           gcs.call("KVGet", {"key": arb}, retries=3))
                if verdict == b"delivered":
                    setattr(self, seq_attr, seq + 1)  # success only
                    setattr(self, attempt_attr, 0)
                    return [pickle.loads(blob)]
                # "withdrawn": the sender gave up on this attempt; its
                # retry (if any) arrives at attempt+1 — move with it.
                attempt += 1
                setattr(self, attempt_attr, attempt)
            _time.sleep(poll)
            poll = min(poll * 2, 0.05)  # backoff: bounded GCS RPC rate
        raise TimeoutError(
            f"recv from rank {opts.src_rank} timed out")

    def destroy_group(self):
        self._compiled.cache_clear()
