"""Base collective group interface (ref:
python/ray/util/collective/collective_group/base_collective_group.py)."""

from __future__ import annotations

from ant_ray_tpu.util.collective import types


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    @classmethod
    def backend(cls) -> str:
        raise NotImplementedError

    def destroy_group(self):
        pass

    # ---- collective verbs

    def allreduce(self, tensors, opts: types.AllReduceOptions):
        raise NotImplementedError

    def allreduce_coalesced(self, tensors,
                            opts: types.AllReduceCoalescedOptions):
        """Fused bucketed allreduce over a tensor list.  Backends
        without a fused path inherit this naive per-tensor loop, so
        the public API works (slowly) on any group."""
        out = []
        for tensor in tensors:
            out.append(self.allreduce(
                [tensor],
                types.AllReduceOptions(reduce_op=opts.reduce_op,
                                       timeout_ms=opts.timeout_ms))[0])
        return out

    def bucket_transfer(self, flat, bucket,
                        opts: types.AllReduceCoalescedOptions):
        """Stage one packed bucket payload toward the backend (host→HBM
        ``device_put`` for xla, torch wrap for gloo).  Exposed per
        bucket so both ``run_coalesced`` and the ready-hook
        ``GradientSyncer`` can drive single buckets."""
        raise NotImplementedError

    def bucket_reduce(self, staged, bucket,
                      opts: types.AllReduceCoalescedOptions):
        """Run one bucket's fused reduction on a staged payload and
        return the reduced flat buffer (accumulated at float32 for
        reduced-precision transports)."""
        raise NotImplementedError

    def fusion_stats(self) -> dict:
        """Cumulative fused-collective stats (device_feed idiom); the
        naive fallback has nothing to report."""
        from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

        stats = getattr(self, "_fusion_stats", None)
        if stats is None:
            stats = self._fusion_stats = fusion.FusionStats()
        return stats.as_dict()

    def barrier(self, opts: types.BarrierOptions):
        raise NotImplementedError

    def reduce(self, tensors, opts: types.ReduceOptions):
        raise NotImplementedError

    def broadcast(self, tensors, opts: types.BroadcastOptions):
        raise NotImplementedError

    def allgather(self, tensors, opts: types.AllGatherOptions):
        raise NotImplementedError

    def reducescatter(self, tensors, opts: types.ReduceScatterOptions):
        raise NotImplementedError

    def send(self, tensors, opts: types.SendOptions):
        raise NotImplementedError

    def recv(self, tensors, opts: types.RecvOptions):
        raise NotImplementedError
