"""Base collective group interface (ref:
python/ray/util/collective/collective_group/base_collective_group.py)."""

from __future__ import annotations

from ant_ray_tpu.util.collective import types


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    @classmethod
    def backend(cls) -> str:
        raise NotImplementedError

    def destroy_group(self):
        pass

    # ---- collective verbs

    def allreduce(self, tensors, opts: types.AllReduceOptions):
        raise NotImplementedError

    def barrier(self, opts: types.BarrierOptions):
        raise NotImplementedError

    def reduce(self, tensors, opts: types.ReduceOptions):
        raise NotImplementedError

    def broadcast(self, tensors, opts: types.BroadcastOptions):
        raise NotImplementedError

    def allgather(self, tensors, opts: types.AllGatherOptions):
        raise NotImplementedError

    def reducescatter(self, tensors, opts: types.ReduceScatterOptions):
        raise NotImplementedError

    def send(self, tensors, opts: types.SendOptions):
        raise NotImplementedError

    def recv(self, tensors, opts: types.RecvOptions):
        raise NotImplementedError
