"""Collective API — verb parity with the reference
(ref: python/ray/util/collective/collective.py — init_collective_group :171,
create_collective_group :211, ops :328-722), NCCL replaced by the ``xla``
backend (XLA collectives over ICI/DCN) and gloo as the CPU fallback.
"""

from __future__ import annotations

import logging
import threading

from ant_ray_tpu.util.collective import types
from ant_ray_tpu.util.collective.types import Backend, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    """Per-process registry of live collective groups
    (ref: collective.py:71)."""

    def __init__(self):
        self._groups: dict[str, object] = {}
        self._lock = threading.Lock()

    def create_group(self, backend: str, world_size: int, rank: int,
                     group_name: str, **kwargs):
        backend = Backend.normalize(backend)
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(
                    f"collective group {group_name!r} already exists")
            if backend == Backend.XLA:
                from ant_ray_tpu.util.collective.collective_group import (  # noqa: PLC0415
                    xla_group,
                )

                group = xla_group.XLAGroup(world_size, rank, group_name,
                                           devices=kwargs.get("devices"))
            else:
                from ant_ray_tpu.util.collective.collective_group import (  # noqa: PLC0415
                    gloo_group,
                )

                init_method = kwargs.get("init_method")
                if init_method is None:
                    init_method = gloo_group.rendezvous_init_method(
                        group_name, rank)
                group = gloo_group.GlooGroup(world_size, rank, group_name,
                                             init_method)
            self._groups[group_name] = group
            return group

    def get_group(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise RuntimeError(
                f"collective group {group_name!r} is not initialized in "
                "this process; call init_collective_group first")
        return group

    def is_group_exist(self, group_name: str) -> bool:
        return group_name in self._groups

    def destroy_group(self, group_name: str):
        with self._lock:
            group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy_group()
        # A re-initialized group must start with a clean tensor-
        # transport slate: stale poisoned-pair markers from the old
        # incarnation would silently dma-degrade the new one forever.
        try:
            from ant_ray_tpu.experimental import tensor_transport  # noqa: PLC0415

            tensor_transport.clear_group(group_name)
        except Exception:  # noqa: BLE001 — healing is best-effort
            pass


_group_mgr = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default", **kwargs):
    """Initialize this process's membership of a collective group
    (ref: collective.py:171)."""
    if world_size <= 0 or not (0 <= rank < world_size):
        raise ValueError(f"invalid rank {rank} / world_size {world_size}")
    return _group_mgr.create_group(backend, world_size, rank, group_name,
                                   **kwargs)


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "xla",
                            group_name: str = "default"):
    """Driver-side declarative group creation over actor handles
    (ref: collective.py:211).  Each actor must expose an
    ``init_collective_group(world_size, rank, backend, group_name)``
    method (mixin: :class:`CollectiveActorMixin`)."""
    import ant_ray_tpu as art  # noqa: PLC0415

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks length mismatch")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(
            f"ranks must be a permutation of 0..{world_size - 1}")
    refs = [
        actor.init_collective_group.remote(world_size, rank, backend,
                                           group_name)
        for actor, rank in zip(actors, ranks)
    ]
    art.get(refs)


class CollectiveActorMixin:
    """Mix into actor classes to make them group-creatable from the driver."""

    def init_collective_group(self, world_size: int, rank: int,
                              backend: str = "xla",
                              group_name: str = "default"):
        init_collective_group(world_size, rank, backend, group_name)
        return True

    def collective_rank(self, group_name: str = "default") -> int:
        return get_rank(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.is_group_exist(group_name)


def destroy_collective_group(group_name: str = "default"):
    _group_mgr.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


# ------------------------------------------------------------------- verbs

def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    return group.allreduce([tensor], types.AllReduceOptions(reduce_op=op))[0]


def allreduce_coalesced(tensors, group_name: str = "default",
                        op: ReduceOp = ReduceOp.SUM, *,
                        bucket_bytes: int = 4 << 20,
                        transport_dtype: "str | None" = None,
                        overlap: bool = True,
                        hierarchy: "types.SliceTopology | None" = None):
    """Fused bucketed allreduce over a list of tensors
    (util/collective/fusion.py): leaves pack into dtype-segregated
    flat buckets of at most ``bucket_bytes``, one collective runs per
    bucket, and bucket k+1's pack + host→device transfer overlaps
    bucket k's collective.  ``transport_dtype="bfloat16"`` opts wide
    float buckets into reduced-precision transport; ``"int8"`` ships
    blockwise-quantized codes + a float32 scale sidecar (~0.25x wire
    bytes, SUM/AVERAGE only; accumulation stays float32 either way).
    ``hierarchy`` (a :class:`~ant_ray_tpu.util.collective.types.
    SliceTopology`) switches to the two-level intra-slice (ICI) /
    inter-slice (DCN) schedule.  Returns the reduced tensors in input
    order."""
    group = _group_mgr.get_group(group_name)
    return group.allreduce_coalesced(
        list(tensors),
        types.AllReduceCoalescedOptions(
            reduce_op=op, bucket_bytes=bucket_bytes,
            transport_dtype=transport_dtype, overlap=overlap,
            hierarchy=hierarchy))


def sync_pytree(tree, group_name: str = "default",
                op: ReduceOp = ReduceOp.AVERAGE, *,
                bucket_bytes: int = 4 << 20,
                transport_dtype: "str | None" = None,
                overlap: bool = True,
                hierarchy: "types.SliceTopology | None" = None):
    """Allreduce every leaf of a pytree through the fused bucketed
    path — the data-parallel gradient-sync verb.  Defaults to AVERAGE
    (gradient semantics); structure is preserved."""
    from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

    leaves, treedef = fusion.flatten_pytree(tree)
    reduced = allreduce_coalesced(
        leaves, group_name=group_name, op=op, bucket_bytes=bucket_bytes,
        transport_dtype=transport_dtype, overlap=overlap,
        hierarchy=hierarchy)
    return fusion.unflatten_pytree(treedef, reduced)


def gradient_syncer(group_name: str = "default",
                    op: ReduceOp = ReduceOp.AVERAGE, *,
                    bucket_bytes: int = 4 << 20,
                    transport_dtype: "str | None" = None,
                    hierarchy: "types.SliceTopology | None" = None,
                    clock=None):
    """A :class:`~ant_ray_tpu.util.collective.fusion.GradientSyncer`
    bound to a live group: the ready-hook gradient sync that launches
    each bucket's collective the moment its last leaf materializes,
    overlapping communication with the rest of the backward pass.
    ``sync_pytree`` is its degenerate one-shot form."""
    import time  # noqa: PLC0415

    from ant_ray_tpu.util.collective import fusion  # noqa: PLC0415

    group = _group_mgr.get_group(group_name)
    opts = types.AllReduceCoalescedOptions(
        reduce_op=op, bucket_bytes=bucket_bytes,
        transport_dtype=transport_dtype, hierarchy=hierarchy)
    return fusion.GradientSyncer(
        group, opts, clock=clock if clock is not None
        else time.perf_counter)


def fusion_stats(group_name: str = "default") -> dict:
    """Cumulative fused-collective stats for a group (pack / transfer /
    collective seconds, overlap fraction — the device_feed stats
    idiom)."""
    return _group_mgr.get_group(group_name).fusion_stats()


def allreduce_multidevice(tensor_list, group_name: str = "default",
                          op: ReduceOp = ReduceOp.SUM):
    """One tensor per local device (parity: allreduce_multigpu)."""
    group = _group_mgr.get_group(group_name)
    return group.allreduce_multidevice(
        tensor_list, types.AllReduceOptions(reduce_op=op))


def barrier(group_name: str = "default"):
    _group_mgr.get_group(group_name).barrier(types.BarrierOptions())


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    return group.reduce(
        [tensor], types.ReduceOptions(reduce_op=op, root_rank=dst_rank))[0]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    return group.broadcast(
        [tensor], types.BroadcastOptions(root_rank=src_rank))[0]


def broadcast_multidevice(tensor_list, src_rank: int = 0,
                          group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    return group.broadcast_multidevice(
        tensor_list, types.BroadcastOptions(root_rank=src_rank))


def allgather(tensor, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    return group.allgather([tensor], types.AllGatherOptions())[0]


def allgather_multidevice(tensor_list, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    return group.allgather_multidevice(tensor_list,
                                       types.AllGatherOptions())


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    return group.reducescatter(
        [tensor], types.ReduceScatterOptions(reduce_op=op))[0]


def reducescatter_multidevice(tensor_list, group_name: str = "default",
                              op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    return group.reducescatter_multidevice(
        tensor_list, types.ReduceScatterOptions(reduce_op=op))


def send(tensor, dst_rank: int, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    group.send([tensor], types.SendOptions(dst_rank=dst_rank))


def recv(tensor, src_rank: int, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    return group.recv([tensor], types.RecvOptions(src_rank=src_rank))[0]
