"""Cluster state inspection API (ref: python/ray/util/state — list/get/
summarize entities served from GCS tables)."""

from __future__ import annotations

from dataclasses import dataclass


def _gcs():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    return global_worker.runtime._gcs


@dataclass
class NodeState:
    node_id: str
    address: str
    alive: bool
    total_resources: dict
    available_resources: dict
    labels: dict


@dataclass
class ActorState:
    actor_id: str
    class_name: str
    state: str
    address: str
    name: str
    death_reason: str
    job_id: str | None = None
    # Hosting node (drain-plane consumers map actors to DRAINING nodes).
    node_id: str | None = None


def list_nodes() -> list[NodeState]:
    nodes = _gcs().call("GetAllNodes", retries=3)
    return [
        NodeState(
            node_id=info.node_id.hex(),
            address=info.address,
            alive=info.alive,
            total_resources=info.total_resources,
            available_resources=info.available_resources,
            labels=info.labels,
        )
        for info in nodes.values()
    ]


def list_actors() -> list[ActorState]:
    records = _gcs().call("ListActors", retries=3)
    return [ActorState(**r) for r in records]


def list_placement_groups() -> dict:
    return _gcs().call("ListPlacementGroups", retries=3)


def list_objects() -> list[dict]:
    """Objects known to the cluster object directory (plasma tier)."""
    return _gcs().call("ListObjects", retries=3)


# State precedence — events may arrive out of order (the driver's
# "submitted" batch can flush after the worker's "finished"), so a
# task's state only ever moves forward through this ranking.
_TASK_STATE_RANK = {"PENDING": 0, "PENDING_EXECUTION": 1, "RUNNING": 2,
                    "FINISHED": 3, "FAILED": 3}


def list_tasks(limit: int = 1000) -> list[dict]:
    """Task lifecycle events aggregated per task (ref: state API
    list_tasks over the GCS task-event table)."""
    events = _gcs().call("TaskEventsGet", {"limit": 50000},
                         retries=3) or []
    by_task: dict[str, dict] = {}
    for event in events:
        record = by_task.setdefault(event["task_id"], {
            "task_id": event["task_id"], "name": event["name"],
            "state": "PENDING", "node_id": "", "actor_id":
            event.get("actor_id")})
        state = {"submitted": "PENDING_EXECUTION",
                 "started": "RUNNING",
                 "finished": "FINISHED",
                 "failed": "FAILED"}.get(event["event"])
        if state is not None and _TASK_STATE_RANK[state] >= \
                _TASK_STATE_RANK[record["state"]]:
            record["state"] = state
        if event["event"] == "started":
            record["node_id"] = event.get("node_id", "")
    return list(by_task.values())[-limit:]


def _matching_node_clients(node_id: str | None):
    """Yield (client, node_id_hex) for every alive node matching the id
    prefix — callers try each until one succeeds (a file lives on ONE
    node; with no node_id given, the right node is unknown a priori)."""
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    nodes = _gcs().call("GetAllNodes", retries=3)
    runtime = global_worker.runtime
    matched = False
    for info in nodes.values():
        if not info.alive:
            continue
        if node_id is None or info.node_id.hex().startswith(node_id):
            matched = True
            yield runtime._clients.get(info.address), info.node_id.hex()
    if not matched:
        raise ValueError(f"no alive node matches {node_id!r}")


def list_logs(node_id: str | None = None) -> dict:
    """Log files available on a node (default: the first alive node).
    (ref: ray.util.state.list_logs via the per-node log agent.)"""
    for client, nid in _matching_node_clients(node_id):
        return {"node_id": nid,
                "files": client.call("ListLogs", {}, retries=3)}
    raise ValueError(f"no alive node matches {node_id!r}")


def get_log(filename: str, node_id: str | None = None, *,
            tail: int | None = None, offset: int = 0,
            max_bytes: int = 65536) -> str:
    """Read a log file from a node without ssh (ref:
    ray.util.state.get_log).  Without a node_id every alive node is
    tried — the file lives on exactly one."""
    last_error = "no nodes"
    for client, _nid in _matching_node_clients(node_id):
        reply = client.call("ReadLog", {
            "filename": filename, "offset": offset, "tail": tail,
            "max_bytes": max_bytes}, retries=3)
        if "error" in reply:
            last_error = reply["error"]
            continue
        return reply["data"].decode("utf-8", errors="replace")
    raise FileNotFoundError(last_error)


def summarize_cluster() -> dict:
    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes": {"alive": sum(n.alive for n in nodes),
                  "dead": sum(not n.alive for n in nodes)},
        "actors": {
            state: sum(1 for a in actors if a.state == state)
            for state in {a.state for a in actors}
        },
        "resources_total": _gcs().call("ClusterResources", retries=3),
        "resources_available": _gcs().call("AvailableResources",
                                           retries=3),
    }
