"""Cluster state inspection API (ref: python/ray/util/state — list/get/
summarize entities served from GCS tables)."""

from __future__ import annotations

from dataclasses import dataclass


def _gcs():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    return global_worker.runtime._gcs


@dataclass
class NodeState:
    node_id: str
    address: str
    alive: bool
    total_resources: dict
    available_resources: dict
    labels: dict


@dataclass
class ActorState:
    actor_id: str
    class_name: str
    state: str
    address: str
    name: str
    death_reason: str


def list_nodes() -> list[NodeState]:
    nodes = _gcs().call("GetAllNodes", retries=3)
    return [
        NodeState(
            node_id=info.node_id.hex(),
            address=info.address,
            alive=info.alive,
            total_resources=info.total_resources,
            available_resources=info.available_resources,
            labels=info.labels,
        )
        for info in nodes.values()
    ]


def list_actors() -> list[ActorState]:
    records = _gcs().call("ListActors", retries=3)
    return [ActorState(**r) for r in records]


def list_placement_groups() -> dict:
    return _gcs().call("ListPlacementGroups", retries=3)


def list_objects() -> list[dict]:
    """Objects known to the cluster object directory (plasma tier)."""
    return _gcs().call("ListObjects", retries=3)


def summarize_cluster() -> dict:
    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes": {"alive": sum(n.alive for n in nodes),
                  "dead": sum(not n.alive for n in nodes)},
        "actors": {
            state: sum(1 for a in actors if a.state == state)
            for state in {a.state for a in actors}
        },
        "resources_total": _gcs().call("ClusterResources", retries=3),
        "resources_available": _gcs().call("AvailableResources",
                                           retries=3),
    }
