"""Cluster state inspection API (ref: python/ray/util/state — list/get/
summarize entities served from GCS tables)."""

from __future__ import annotations

from dataclasses import dataclass


def _gcs():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    return global_worker.runtime._gcs


def _client_pool():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    return global_worker.runtime._clients


@dataclass
class NodeState:
    node_id: str
    address: str
    alive: bool
    total_resources: dict
    available_resources: dict
    labels: dict


@dataclass
class ActorState:
    actor_id: str
    class_name: str
    state: str
    address: str
    name: str
    death_reason: str
    job_id: str | None = None
    # Hosting node (drain-plane consumers map actors to DRAINING nodes).
    node_id: str | None = None


def list_nodes() -> list[NodeState]:
    nodes = _gcs().call("GetAllNodes", retries=3)
    return [
        NodeState(
            node_id=info.node_id.hex(),
            address=info.address,
            alive=info.alive,
            total_resources=info.total_resources,
            available_resources=info.available_resources,
            labels=info.labels,
        )
        for info in nodes.values()
    ]


def list_actors() -> list[ActorState]:
    records = _gcs().call("ListActors", retries=3)
    return [ActorState(**r) for r in records]


def list_placement_groups() -> dict:
    return _gcs().call("ListPlacementGroups", retries=3)


def list_objects(*, joined: bool = True) -> list[dict]:
    """Objects known to the cluster: the GCS directory joined with
    per-daemon residency (size, pins, storage tier, chunk-cache bytes)
    — the same join ``art memory`` and ``/api/objects`` render.
    ``joined=False`` returns the raw directory only."""
    if not joined:
        return _gcs().call("ListObjects", retries=3)
    from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
        list_objects_joined,
    )

    return list_objects_joined(_gcs(), _client_pool())


def memory_report(top_n: int = 20) -> dict:
    """Per-node object-store usage, top-N objects by size with
    owner/holders/pin attribution, and leak candidates (the ``ray
    memory`` analog; `art memory` renders this)."""
    from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
        build_memory_report,
    )

    return build_memory_report(_gcs(), _client_pool(), top_n=top_n)


def list_jobs() -> list[dict]:
    """Driver jobs registered with the GCS."""
    return _gcs().call("ListJobs", retries=3)


# State precedence for the thin-client fallback fold — events may
# arrive out of order (the driver's "submitted" batch can flush after
# the worker's "finished"), so a task's state only ever moves forward
# through this ranking, and terminal states are sticky (FINISHED and
# FAILED share a rank: a late duplicate flush must never flip one into
# the other).
_TASK_STATE_RANK = {"PENDING": 0, "PENDING_EXECUTION": 1, "RUNNING": 2,
                    "FINISHED": 3, "FAILED": 3}
_TERMINAL = ("FINISHED", "FAILED")


def _is_no_route(error: Exception) -> bool:
    return "no route for method" in str(error)


def list_tasks(limit: int = 1000, *, state: str | None = None,
               name: str | None = None, job_id: str | None = None,
               actor_id: str | None = None, node_id: str | None = None,
               token: int | None = None) -> list[dict]:
    """Per-(task, attempt) state records, filtered SERVER-SIDE from the
    bounded GCS state table (ref: the state API's ListTasks over
    GcsTaskManager's task table) — the raw event ring never crosses
    the wire.  Against a pre-observatory server, falls back to the thin
    client-side fold."""
    from ant_ray_tpu._private.protocol import RpcError  # noqa: PLC0415

    try:
        reply = _gcs().call("ListTasks", {
            "state": state, "name": name, "job_id": job_id,
            "actor_id": actor_id, "node_id": node_id,
            "limit": limit, "token": token}, retries=3)
        return reply["tasks"]
    except RpcError as e:
        if not _is_no_route(e):
            raise
    return _list_tasks_fallback(limit, state=state, name=name,
                                job_id=job_id, actor_id=actor_id,
                                node_id=node_id)


def list_tasks_page(limit: int = 1000, token: int | None = None,
                    **filters) -> dict:
    """Paginated variant: the full ListTasks reply ({tasks,
    next_token, num_tasks_dropped, task_events_dropped})."""
    return _gcs().call("ListTasks",
                       {"limit": limit, "token": token, **filters},
                       retries=3)


def _list_tasks_fallback(limit: int, *, state=None, name=None,
                         job_id=None, actor_id=None,
                         node_id=None) -> list[dict]:
    """Client-side fold of the raw event ring, keyed by (task_id,
    attempt) with sticky terminal states — kept only for talking to
    old servers without the GCS state table."""
    events = _gcs().call("TaskEventsGet", {"limit": 50000},
                         retries=3) or []
    by_attempt: dict[tuple, dict] = {}
    for event in events:
        key = (event["task_id"], int(event.get("attempt") or 0))
        record = by_attempt.setdefault(key, {
            "task_id": event["task_id"], "attempt": key[1],
            "name": event["name"], "state": "PENDING", "node_id": "",
            "job_id": event.get("job_id"),
            "actor_id": event.get("actor_id")})
        new = {"submitted": "PENDING_EXECUTION",
               "started": "RUNNING",
               "finished": "FINISHED",
               "failed": "FAILED"}.get(event["event"])
        # Forward-only: strictly-higher rank moves the state, so an
        # equal-rank late "finished" can never overwrite FAILED, and a
        # terminal state never regresses to a retried-flush "started".
        if new is not None and _TASK_STATE_RANK[new] > \
                _TASK_STATE_RANK[record["state"]]:
            record["state"] = new
        if event["event"] == "started":
            record["node_id"] = event.get("node_id", "")
    out = [r for r in by_attempt.values()
           if (not state or r["state"] == state)
           and (not name or r["name"] == name)
           and (not job_id or r["job_id"] == job_id)
           and (not actor_id or r["actor_id"] == actor_id)
           and (not node_id or r["node_id"].startswith(node_id))]
    return out[-limit:]


def get_task(task_id: str) -> dict | None:
    """Every attempt of one task plus table stats (GetTask)."""
    return _gcs().call("GetTask", {"task_id": task_id}, retries=3)


def summarize_tasks(job_id: str | None = None) -> dict:
    """Group-by-name task rollup (per-state counts, run-duration
    mean/p50/p99) computed server-side (SummarizeTasks)."""
    return _gcs().call("SummarizeTasks", {"job_id": job_id}, retries=3)


def _matching_node_clients(node_id: str | None):
    """Yield (client, node_id_hex) for every alive node matching the id
    prefix — callers try each until one succeeds (a file lives on ONE
    node; with no node_id given, the right node is unknown a priori)."""
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    nodes = _gcs().call("GetAllNodes", retries=3)
    runtime = global_worker.runtime
    matched = False
    for info in nodes.values():
        if not info.alive:
            continue
        if node_id is None or info.node_id.hex().startswith(node_id):
            matched = True
            yield runtime._clients.get(info.address), info.node_id.hex()
    if not matched:
        raise ValueError(f"no alive node matches {node_id!r}")


def list_logs(node_id: str | None = None) -> dict:
    """Log files available on a node (default: the first alive node).
    (ref: ray.util.state.list_logs via the per-node log agent.)"""
    for client, nid in _matching_node_clients(node_id):
        return {"node_id": nid,
                "files": client.call("ListLogs", {}, retries=3)}
    raise ValueError(f"no alive node matches {node_id!r}")


def get_log(filename: str, node_id: str | None = None, *,
            tail: int | None = None, offset: int = 0,
            max_bytes: int = 65536) -> str:
    """Read a log file from a node without ssh (ref:
    ray.util.state.get_log).  Without a node_id every alive node is
    tried — the file lives on exactly one."""
    last_error = "no nodes"
    for client, _nid in _matching_node_clients(node_id):
        reply = client.call("ReadLog", {
            "filename": filename, "offset": offset, "tail": tail,
            "max_bytes": max_bytes}, retries=3)
        if "error" in reply:
            last_error = reply["error"]
            continue
        return reply["data"].decode("utf-8", errors="replace")
    raise FileNotFoundError(last_error)


def summarize_cluster() -> dict:
    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes": {"alive": sum(n.alive for n in nodes),
                  "dead": sum(not n.alive for n in nodes)},
        "actors": {
            state: sum(1 for a in actors if a.state == state)
            for state in {a.state for a in actors}
        },
        "resources_total": _gcs().call("ClusterResources", retries=3),
        "resources_available": _gcs().call("AvailableResources",
                                           retries=3),
    }
