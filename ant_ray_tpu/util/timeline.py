"""Chrome-trace timeline export (ref capability: ``ray timeline`` —
python/ray/_private/state.py chrome_tracing_dump over GCS task events).

``timeline()`` pairs each task's started/finished events into complete
("ph": "X") slices — rows grouped by node (pid) and worker process
(tid) — plus flow arrows ("s"/"f") from submission to execution, so
chrome://tracing / Perfetto renders the cluster's task schedule with
cross-process causality.
"""

from __future__ import annotations

import json


def fetch_task_events(limit: int = 50000) -> list[dict]:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    from ant_ray_tpu._private import task_events  # noqa: PLC0415

    task_events.flush()  # this process's tail
    return runtime._gcs.call("TaskEventsGet", {"limit": limit},
                             retries=3) or []


def build_chrome_trace(events: list[dict]) -> list[dict]:
    by_task: dict[str, dict] = {}
    for event in events:
        record = by_task.setdefault(event["task_id"], {"events": {}})
        record["events"][event["event"]] = event
    trace: list[dict] = []
    flow_id = 0
    for task_id, record in by_task.items():
        started = record["events"].get("started")
        done = (record["events"].get("finished")
                or record["events"].get("failed"))
        submitted = record["events"].get("submitted")
        if started is None:
            continue
        pid = started.get("node_id") or "node"
        tid = f"worker-{started.get('pid', 0)}"
        ts_us = started["ts"] * 1e6
        dur_us = ((done["ts"] - started["ts"]) * 1e6
                  if done is not None else 0.0)
        failed = "failed" in record["events"]
        trace.append({
            "ph": "X", "cat": "task",
            "name": started.get("name", task_id),
            "pid": pid, "tid": tid, "ts": ts_us, "dur": dur_us,
            "args": {"task_id": task_id,
                     # the parent is known at submission (the driver or
                     # the executing task that spawned this one)
                     "parent_task_id": (submitted or started).get(
                         "parent_task_id"),
                     "status": "failed" if failed else "ok"},
            **({"cname": "terrible"} if failed else {}),
        })
        if submitted is not None:
            flow_id += 1
            trace.append({
                "ph": "s", "cat": "submit", "id": flow_id,
                "name": "submit",
                "pid": submitted.get("node_id") or "driver",
                "tid": f"worker-{submitted.get('pid', 0)}",
                "ts": submitted["ts"] * 1e6})
            trace.append({
                "ph": "f", "cat": "submit", "id": flow_id,
                "name": "submit", "bp": "e",
                "pid": pid, "tid": tid, "ts": ts_us})
    return trace


def timeline(filename: str | None = None) -> list[dict] | str:
    """Chrome trace of the cluster's task schedule.  With ``filename``
    writes the JSON and returns the path (load in chrome://tracing or
    https://ui.perfetto.dev); without, returns the event list."""
    trace = build_chrome_trace(fetch_task_events())
    if filename is None:
        return trace
    with open(filename, "w") as f:
        json.dump(trace, f)
    return filename
