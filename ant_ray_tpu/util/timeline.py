"""Chrome-trace timeline export (ref capability: ``ray timeline`` —
python/ray/_private/state.py chrome_tracing_dump over GCS task events).

``timeline()`` pairs each task's started/finished events into complete
("ph": "X") slices — rows grouped by node (pid) and worker process
(tid) — plus flow arrows ("s"/"f") from submission to execution, so
chrome://tracing / Perfetto renders the cluster's task schedule with
cross-process causality.

Step-profiler records (observability/step_profiler.py) merge in as
per-rank "device" rows: one ``train-step/rank-N`` track per rank, each
step a slice subdivided into its phases (data_wait → h2d → compute →
collective, canonical order, measured durations) — so Perfetto shows
compute vs. transfer vs. collective right next to the task schedule.
"""

from __future__ import annotations

import json

# Canonical within-step phase order for the rendered sub-slices (phase
# seconds are attributions, not a measured schedule — see
# observability/step_profiler.py).
_STEP_PHASE_ORDER = ("data_wait", "h2d", "compute", "collective")


def fetch_task_events(limit: int = 50000) -> list[dict]:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    from ant_ray_tpu._private import task_events  # noqa: PLC0415

    task_events.flush()  # this process's tail
    return runtime._gcs.call("TaskEventsGet", {"limit": limit},
                             retries=3) or []


def fetch_step_events(limit: int = 20000) -> list[dict]:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    try:
        return runtime._gcs.call("StepEventsGet", {"limit": limit},
                                 retries=3) or []
    except Exception:  # noqa: BLE001 — pre-upgrade GCS without the table
        return []


def fetch_span_events(limit: int = 50000,
                      trace_id: str | None = None) -> list[dict]:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

    tracing_plane.flush()  # this process's pending tail
    payload: dict = {"limit": limit}
    if trace_id is not None:
        payload["trace_id"] = trace_id
    try:
        return runtime._gcs.call("SpanEventsGet", payload,
                                 retries=3) or []
    except Exception:  # noqa: BLE001 — pre-upgrade GCS without the ring
        return []


def fetch_cpu_profile(limit: int = 4000) -> list[dict]:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    try:
        return runtime._gcs.call("CpuProfileGet", {"limit": limit},
                                 retries=3) or []
    except Exception:  # noqa: BLE001 — pre-upgrade GCS without the ring
        return []


def build_cpu_profile_rows(profile_records: list[dict]) -> list[dict]:
    """Sampler-publication rows from the continuous CPU profiler
    (observability/cpu_profiler.py): one ``cpu-profile/<proc>-<pid>``
    track per publishing process, each publication window an "X" slice
    whose args carry the window's heaviest folded stacks — the
    wall-clock task schedule above, where the CPU actually went below."""
    trace: list[dict] = []
    pid = "cpu-profile"
    for rec in profile_records:
        dur = float(rec.get("dur_s", 0.0))
        if dur <= 0:
            continue
        ts_us = (float(rec.get("ts", 0.0)) - dur) * 1e6
        node8 = str(rec.get("node_id", ""))[:8]
        tid = f"{rec.get('proc', '?')}-{rec.get('pid', 0)}"
        stacks = rec.get("stacks") or {}
        top = sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
        args = {"node_id": node8, "samples": rec.get("samples"),
                "hz": rec.get("hz")}
        for rank, (stack, count) in enumerate(top, start=1):
            args[f"top{rank}"] = f"{count} {stack}"
        trace.append({
            "ph": "X", "cat": "cpu_profile",
            "name": f"samples={rec.get('samples', 0)}",
            "pid": pid, "tid": tid, "ts": ts_us, "dur": dur * 1e6,
            "args": args,
        })
    return trace


def build_request_rows(span_events: list[dict]) -> list[dict]:
    """Per-request rows from published trace spans
    (observability/tracing_plane.py): one ``request/<trace8>`` track per
    trace, each span an "X" slice (args carry stage seconds, node, pid,
    error) — Perfetto shows a serve request's ingress → router →
    replica → nested task → object-pull decomposition next to the task
    schedule."""
    trace: list[dict] = []
    pid = "request"
    for s in span_events:
        dur = float(s.get("dur_s", 0.0))
        ts_us = float(s.get("ts", 0.0)) * 1e6
        tid = str(s.get("trace_id", ""))[:8]
        args = {"trace_id": s.get("trace_id"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "node_id": s.get("node_id"), "pid": s.get("pid")}
        for stage, sec in (s.get("stages") or {}).items():
            args[f"{stage}_s"] = round(float(sec), 6)
        args.update(s.get("attrs") or {})
        if s.get("error"):
            args["error"] = True
        trace.append({
            "ph": "X", "cat": "request_span",
            "name": s.get("name", "span"),
            "pid": pid, "tid": tid, "ts": ts_us, "dur": dur * 1e6,
            "args": args,
            **({"cname": "terrible"} if s.get("error") else {}),
        })
    return trace


def build_step_rows(step_events: list[dict]) -> list[dict]:
    """Per-rank device rows from published step records: one "X" slice
    per step ("step N", args carry phase seconds + MFU) and nested "X"
    sub-slices per phase in canonical order."""
    trace: list[dict] = []
    pid = "train-step"
    for rec in step_events:
        total = float(rec.get("total_s", 0.0))
        ts0_us = float(rec.get("ts", 0.0)) * 1e6
        if total <= 0:
            continue
        tid = f"rank-{int(rec.get('rank', 0))}"
        phases = {k: float(v)
                  for k, v in (rec.get("phases") or {}).items()}
        args = {f"{name}_s": round(sec, 6)
                for name, sec in sorted(phases.items())}
        if rec.get("mfu") is not None:
            args["mfu"] = rec["mfu"]
        trace.append({
            "ph": "X", "cat": "train_step",
            "name": f"step {int(rec.get('step', 0))}",
            "pid": pid, "tid": tid, "ts": ts0_us, "dur": total * 1e6,
            "args": args,
        })
        cursor = ts0_us
        end_us = ts0_us + total * 1e6
        ordered = [p for p in _STEP_PHASE_ORDER if p in phases]
        ordered += sorted(p for p in phases if p not in _STEP_PHASE_ORDER)
        for name in ordered:
            # Clamp into the parent slice: attributions can over-count
            # (an attached stream overlapping an explicit phase block)
            # and Perfetto rejects children escaping their parent.
            dur_us = min(phases[name] * 1e6, end_us - cursor)
            if dur_us <= 0:
                continue
            trace.append({
                "ph": "X", "cat": "step_phase", "name": name,
                "pid": pid, "tid": tid, "ts": cursor, "dur": dur_us,
            })
            cursor += dur_us
    return trace


def build_chrome_trace(events: list[dict],
                       step_events: list[dict] | None = None,
                       span_events: list[dict] | None = None,
                       cpu_profile: list[dict] | None = None
                       ) -> list[dict]:
    by_task: dict[str, dict] = {}
    for event in events:
        record = by_task.setdefault(event["task_id"], {"events": {}})
        record["events"][event["event"]] = event
    trace: list[dict] = []
    flow_id = 0
    for task_id, record in by_task.items():
        started = record["events"].get("started")
        done = (record["events"].get("finished")
                or record["events"].get("failed"))
        submitted = record["events"].get("submitted")
        if started is None:
            continue
        pid = started.get("node_id") or "node"
        tid = f"worker-{started.get('pid', 0)}"
        ts_us = started["ts"] * 1e6
        dur_us = ((done["ts"] - started["ts"]) * 1e6
                  if done is not None else 0.0)
        failed = "failed" in record["events"]
        trace.append({
            "ph": "X", "cat": "task",
            "name": started.get("name", task_id),
            "pid": pid, "tid": tid, "ts": ts_us, "dur": dur_us,
            "args": {"task_id": task_id,
                     # the parent is known at submission (the driver or
                     # the executing task that spawned this one)
                     "parent_task_id": (submitted or started).get(
                         "parent_task_id"),
                     "status": "failed" if failed else "ok"},
            **({"cname": "terrible"} if failed else {}),
        })
        if submitted is not None:
            flow_id += 1
            trace.append({
                "ph": "s", "cat": "submit", "id": flow_id,
                "name": "submit",
                "pid": submitted.get("node_id") or "driver",
                "tid": f"worker-{submitted.get('pid', 0)}",
                "ts": submitted["ts"] * 1e6})
            trace.append({
                "ph": "f", "cat": "submit", "id": flow_id,
                "name": "submit", "bp": "e",
                "pid": pid, "tid": tid, "ts": ts_us})
    if step_events:
        trace.extend(build_step_rows(step_events))
    if span_events:
        trace.extend(build_request_rows(span_events))
    if cpu_profile:
        trace.extend(build_cpu_profile_rows(cpu_profile))
    return trace


def timeline(filename: str | None = None) -> list[dict] | str:
    """Chrome trace of the cluster's task schedule — plus, when a step
    profiler published records, per-rank step-phase device rows, and
    when request traces were sampled, per-request span rows.  With
    ``filename`` writes the JSON and returns the path (load in
    chrome://tracing or https://ui.perfetto.dev); without, returns the
    event list."""
    trace = build_chrome_trace(fetch_task_events(),
                               step_events=fetch_step_events(),
                               span_events=fetch_span_events(),
                               cpu_profile=fetch_cpu_profile())
    if filename is None:
        return trace
    with open(filename, "w") as f:
        json.dump(trace, f)
    return filename
