"""Virtual clusters: multi-tenant partitioning of one physical cluster
(ant-fork capability, ref: src/ray/gcs/gcs_virtual_cluster_manager.h:30,
gcs_virtual_cluster.h:154 — DivisibleCluster/IndivisibleCluster/
PrimaryCluster reduced to their scheduling-visible core).

Jobs bound to a virtual cluster schedule only on its nodes; unbound
jobs schedule on the unassigned remainder (the "primary cluster").
"""

from __future__ import annotations


def _gcs():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    return global_worker.runtime._gcs


def _to_node_ids(node_ids_hex):
    from ant_ray_tpu._private.ids import NodeID  # noqa: PLC0415

    return [NodeID.from_hex(h) for h in node_ids_hex]


def create_virtual_cluster(vc_id: str, *, node_ids: list | None = None,
                           num_nodes: int | None = None,
                           divisible: bool = False) -> dict:
    """Carve a virtual cluster out of unassigned nodes: either the
    explicit hex ``node_ids`` or ``num_nodes`` picked from the free
    pool."""
    payload = {"vc_id": vc_id, "divisible": divisible,
               "num_nodes": num_nodes}
    if node_ids:
        payload["node_ids"] = _to_node_ids(node_ids)
    reply = _gcs().call("CreateVirtualCluster", payload, retries=3)
    if "error" in reply:
        raise ValueError(reply["error"])
    return reply


def remove_virtual_cluster(vc_id: str) -> bool:
    return _gcs().call("RemoveVirtualCluster", {"vc_id": vc_id},
                       retries=3)


def update_virtual_cluster(vc_id: str, *, add_nodes: list | None = None,
                           remove_nodes: list | None = None) -> dict:
    reply = _gcs().call("UpdateVirtualCluster", {
        "vc_id": vc_id,
        "add_nodes": _to_node_ids(add_nodes or []),
        "remove_nodes": _to_node_ids(remove_nodes or []),
    }, retries=3)
    if "error" in reply:
        raise ValueError(reply["error"])
    return reply


def list_virtual_clusters() -> dict:
    return _gcs().call("ListVirtualClusters", retries=3)


def bind_job(vc_id: str | None) -> None:
    """Bind the CURRENT job to a virtual cluster (None unbinds).  The
    reference assigns jobs at submission; rebinding mid-job affects
    tasks scheduled from now on."""
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    runtime = global_worker.runtime
    reply = _gcs().call("SetJobVirtualCluster", {
        "job_id": runtime.job_id, "vc_id": vc_id}, retries=3)
    if isinstance(reply, dict) and "error" in reply:
        raise ValueError(reply["error"])
