"""Scheduling strategies (capability mirror of
ray.util.scheduling_strategies + the raylet's scheduling policy set,
ref: src/ray/raylet/scheduling/policy/composite_scheduling_policy.h:33 —
hybrid pack/spread default, SPREAD, node affinity):

* ``"DEFAULT"`` / ``None`` — hybrid: pack onto busier feasible nodes
  until they pass the utilization threshold, then spread to the
  least-loaded (ref: hybrid_scheduling_policy.h).
* ``"SPREAD"`` — round-robin across feasible nodes (ref:
  spread_scheduling_policy.h).
* :class:`NodeAffinitySchedulingStrategy` — pin to one node; ``soft``
  falls back to DEFAULT when the node is gone (ref:
  node_affinity_scheduling_policy.h).

Pass via ``@art.remote(scheduling_strategy=...)`` or
``.options(scheduling_strategy=...)`` on tasks and actors.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class NodeAffinitySchedulingStrategy:
    """Run on the node with this id (hex string, from ``art.nodes()``
    or ``ART_NODE_ID`` inside a worker)."""

    node_id: str
    soft: bool = False

    def wire(self) -> dict:
        return {"kind": "node_affinity", "node_id": self.node_id,
                "soft": self.soft}


def strategy_wire(strategy) -> dict | str | None:
    """Normalize a user strategy to its picklable wire form."""
    if strategy is None or strategy == "DEFAULT":
        return None
    if strategy == "SPREAD":
        return "SPREAD"
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return strategy.wire()
    raise ValueError(
        f"unknown scheduling_strategy {strategy!r}; expected 'DEFAULT', "
        "'SPREAD', or NodeAffinitySchedulingStrategy")
