"""TPU slice reservation — topology-aware gang scheduling.

Parity target: ``SlicePlacementGroup`` / ``slice_placement_group()``
(ref: python/ray/util/tpu.py:52,227) and ``reserve_tpu_slice``
(ref: python/ray/_private/accelerators/tpu.py:213).  Redesigned
TPU-first: instead of the reference's two-step dance (reserve the
``TPU-<pod>-head`` resource, fetch the slice name, then build a second
PG), the GCS placement planner natively supports a *same-label*
constraint — one placement group whose bundles must all land on nodes
sharing a ``tpu-pod-name`` — so a whole multi-host slice is reserved
atomically with the existing 2-phase bundle commit.

Rank→host mapping is deterministic: bundle ``i`` carries the label
selector ``{"tpu-worker-id": str(i)}``, so worker ``i`` of the training
job sits on TPU host ``i`` of the slice, matching the ICI torus layout
the sharded program expects.
"""

from __future__ import annotations

from dataclasses import dataclass

from ant_ray_tpu._private.accelerators import tpu as tpu_accel
from ant_ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)


@dataclass(frozen=True)
class SlicePlacementGroup:
    """A reserved (or reserving) whole TPU slice.

    ``placement_group`` holds one bundle per TPU host; task/actor
    ``options(placement_group=..., placement_group_bundle_index=rank)``
    pins each rank to its host.
    """

    placement_group: PlacementGroup
    topology: str
    generation: str
    num_hosts: int
    chips_per_host: int

    @property
    def pod_type(self) -> str:
        return tpu_accel.infer_pod_type(self.topology, self.generation)

    @property
    def num_chips(self) -> int:
        return tpu_accel.topology_chip_count(self.topology)

    def ready(self, timeout: float = 100.0) -> bool:
        return self.placement_group.ready(timeout=timeout)

    def remove(self) -> None:
        remove_placement_group(self.placement_group)


def slice_placement_group(topology: str,
                          accelerator_type: str = "TPU-V5E",
                          name: str = "",
                          bundle_extra: dict | None = None
                          ) -> SlicePlacementGroup:
    """Reserve one whole TPU slice of ``topology`` (e.g. "4x8").

    Every bundle lands on a node advertising the same ``tpu-pod-name``
    (one physical slice), bundle i on the host with
    ``tpu-worker-id == i``; bundle 0 additionally reserves the
    ``TPU-<pod_type>-head`` resource so at most one job owns a slice
    (ref: TPU-<pod>-head reservation, python/ray/util/tpu.py:227).
    """
    generation = tpu_accel.normalize_generation(accelerator_type)
    num_hosts = tpu_accel.hosts_in_slice(topology, generation)
    chips = tpu_accel.chips_per_host(topology, generation)
    pod_type = tpu_accel.infer_pod_type(topology, generation)

    bundles: list[dict] = []
    selectors: list[dict] = []
    for host in range(num_hosts):
        # bundle_extra: per-host resources the gang's actors will demand
        # beyond chips (typically {"CPU": 1}) — reserved here so the
        # bundle can actually host them.
        bundle = {"TPU": float(chips), **(bundle_extra or {})}
        if host == 0:
            bundle[f"TPU-{pod_type}-head"] = 1.0
        bundles.append(bundle)
        selectors.append({"tpu-worker-id": str(host),
                          "tpu-generation": generation})

    pg = placement_group(
        bundles,
        strategy="STRICT_SPREAD" if num_hosts > 1 else "STRICT_PACK",
        name=name or f"slice-{pod_type}",
        bundle_label_selectors=selectors,
        _same_label="tpu-pod-name" if num_hosts > 1 else None,
    )
    return SlicePlacementGroup(
        placement_group=pg, topology=topology, generation=generation,
        num_hosts=num_hosts, chips_per_host=chips)


@dataclass(frozen=True)
class MultiSlicePlacementGroup:
    """A reserved gang spanning ``num_slices`` whole TPU slices.

    ONE placement group holds ``num_slices * hosts_per_slice`` bundles
    in contiguous per-slice blocks: bundle ``s * hosts_per_slice + i``
    is host ``i`` of slice ``s``.  The GCS planner's same-label-groups
    constraint pins each block to one ``tpu-pod-name`` and distinct
    blocks to distinct pods, so the whole multi-slice reservation
    commits (or rolls back) atomically.  The matching rank→slice
    partition for the hierarchical allreduce is
    ``SliceTopology.regular(num_hosts, num_slices)``.
    """

    placement_group: PlacementGroup
    topology: str
    generation: str
    num_slices: int
    hosts_per_slice: int
    chips_per_host: int

    @property
    def num_hosts(self) -> int:
        return self.num_slices * self.hosts_per_slice

    @property
    def pod_type(self) -> str:
        return tpu_accel.infer_pod_type(self.topology, self.generation)

    def slice_of_bundle(self, index: int) -> int:
        return index // self.hosts_per_slice

    def ready(self, timeout: float = 100.0) -> bool:
        return self.placement_group.ready(timeout=timeout)

    def remove(self) -> None:
        remove_placement_group(self.placement_group)


def multi_slice_placement_group(topology: str,
                                num_slices: int,
                                accelerator_type: str = "TPU-V5E",
                                name: str = "",
                                bundle_extra: dict | None = None
                                ) -> MultiSlicePlacementGroup:
    """Reserve ``num_slices`` whole TPU slices of ``topology`` each —
    the multi-slice (DCN data-parallel) gang reservation.

    Per slice s: bundle ``s * num_hosts + i`` lands on the host with
    ``tpu-worker-id == i`` of one physical slice (all of slice s's
    bundles share a ``tpu-pod-name``; distinct s get distinct pods),
    and bundle ``s * num_hosts`` additionally reserves that pod's
    ``TPU-<pod_type>-head`` resource so no other job grabs the slice.
    """
    if num_slices <= 0:
        raise ValueError(f"num_slices must be positive, got {num_slices}")
    generation = tpu_accel.normalize_generation(accelerator_type)
    num_hosts = tpu_accel.hosts_in_slice(topology, generation)
    chips = tpu_accel.chips_per_host(topology, generation)
    pod_type = tpu_accel.infer_pod_type(topology, generation)

    bundles: list[dict] = []
    selectors: list[dict] = []
    groups: list[list[int]] = []
    for s in range(num_slices):
        groups.append(list(range(s * num_hosts, (s + 1) * num_hosts)))
        for host in range(num_hosts):
            bundle = {"TPU": float(chips), **(bundle_extra or {})}
            if host == 0:
                bundle[f"TPU-{pod_type}-head"] = 1.0
            bundles.append(bundle)
            selectors.append({"tpu-worker-id": str(host),
                              "tpu-generation": generation})

    pg = placement_group(
        bundles,
        strategy="STRICT_SPREAD" if num_hosts > 1 else "PACK",
        name=name or f"multislice-{pod_type}x{num_slices}",
        bundle_label_selectors=selectors,
        _same_label="tpu-pod-name",
        _same_label_groups=groups,
    )
    return MultiSlicePlacementGroup(
        placement_group=pg, topology=topology, generation=generation,
        num_slices=num_slices, hosts_per_slice=num_hosts,
        chips_per_host=chips)
