"""Placement groups: atomic gang reservation of resource bundles
(ref: src/ray/gcs/gcs_placement_group_manager.h:55, bundle policies
src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h:81-105,
python/ray/util/placement_group.py API).

Strategies: PACK (prefer one node), SPREAD (prefer distinct nodes),
STRICT_PACK (must be one node), STRICT_SPREAD (must be distinct nodes).
Reservation is two-phase (prepare on every node, then commit; any prepare
failure rolls back) so concurrent groups can't deadlock on partial
reservations.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ant_ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass(frozen=True)
class PlacementGroup:
    id: PlacementGroupID
    bundles: tuple
    strategy: str

    def ready(self, timeout: float = 30.0) -> bool:
        """Block until the group is reserved (ref: pg.ready())."""
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        runtime = global_worker.runtime
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            state = runtime._gcs.call(
                "GetPlacementGroup", {"pg_id": self.id}, retries=3)
            if state is None:
                raise ValueError("placement group was removed")
            if state["state"] == "CREATED":
                return True
            if state["state"] == "FAILED":
                raise RuntimeError(
                    f"placement group infeasible: {state.get('reason', '')}")
            time.sleep(0.05)
        return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def bundle_node(self, index: int):
        """Node address hosting a bundle (for debugging/tests)."""
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        state = global_worker.runtime._gcs.call(
            "GetPlacementGroup", {"pg_id": self.id}, retries=3)
        return state["bundle_nodes"][index] if state else None


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "",
                    bundle_label_selectors: list[dict] | None = None,
                    _same_label: str | None = None,
                    _same_label_groups: "list | None" = None
                    ) -> PlacementGroup:
    """``bundle_label_selectors``: optional per-bundle node-label
    constraints (ref: bundle_label_selector in reserve_tpu_slice,
    python/ray/_private/accelerators/tpu.py:213).  ``_same_label``: a
    label key whose value must be shared by every bundle's node — the
    slice-affinity primitive behind slice_placement_group().
    ``_same_label_groups``: lists of bundle indices; each group's nodes
    share one ``_same_label`` value and distinct groups get DISTINCT
    values — the multi-slice primitive (one group per physical slice)
    behind multi_slice_placement_group()."""
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    if bundle_label_selectors is not None and \
            len(bundle_label_selectors) != len(bundles):
        raise ValueError("bundle_label_selectors must match bundles 1:1")
    global_worker._check_connected()
    runtime = global_worker.runtime
    pg_id = PlacementGroupID.of(runtime.job_id)
    runtime._gcs.call("CreatePlacementGroup", {
        "pg_id": pg_id,
        "bundles": [dict(b) for b in bundles],
        "strategy": strategy,
        "name": name,
        "job_id": runtime.job_id,  # VC-aware bundle placement
        "bundle_label_selectors": bundle_label_selectors,
        "same_label": _same_label,
        "same_label_groups": ([list(g) for g in _same_label_groups]
                              if _same_label_groups else None),
    }, retries=3)
    return PlacementGroup(pg_id, tuple(tuple(sorted(b.items()))
                                       for b in bundles), strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker.runtime._gcs.call(
        "RemovePlacementGroup", {"pg_id": pg.id}, retries=3)


def placement_group_table() -> dict:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    return global_worker.runtime._gcs.call(
        "ListPlacementGroups", retries=3)
