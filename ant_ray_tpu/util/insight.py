"""Flow insight: runtime call-graph / dataflow tracing (ant-fork
capability, ref: python/ray/util/insight.py:12-26 CallSubmitEvent /
CallBeginEvent / ObjectGet/Put events + dashboard/modules/insight/).

Workers and drivers emit lightweight events (oneway RPC, enabled by
``Config.enable_insight``) into a bounded GCS ring buffer; the
dashboard serves them at ``/api/insight`` and
:func:`build_call_graph` aggregates caller→callee edges with counts
and latency for visualization.
"""

from __future__ import annotations

import time


def _enabled_runtime():
    from ant_ray_tpu._private.config import global_config  # noqa: PLC0415
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if not global_config().enable_insight:
        return None
    if not global_worker.connected:
        return None
    runtime = global_worker.runtime
    return runtime if hasattr(runtime, "_send_oneway") else None


def emit(event_type: str, **fields) -> None:
    """Record one flow event (best-effort oneway)."""
    runtime = _enabled_runtime()
    if runtime is None:
        return
    payload = {"type": event_type, "ts": time.time(),
               "source": runtime.address, **fields}
    runtime._send_oneway(runtime.gcs_address, "InsightRecord", payload)


def record_call_submit(function_name: str, task_id_hex: str,
                       caller: str) -> None:
    emit("call_submit", function=function_name, task_id=task_id_hex,
         caller=caller)


def record_call_begin(function_name: str, task_id_hex: str) -> None:
    emit("call_begin", function=function_name, task_id=task_id_hex)


def record_call_end(function_name: str, task_id_hex: str,
                    duration_s: float, error: bool = False) -> None:
    emit("call_end", function=function_name, task_id=task_id_hex,
         duration_s=duration_s, error=error)


def record_object_put(object_id_hex: str, size: int) -> None:
    emit("object_put", object_id=object_id_hex, size=size)


def record_object_get(object_id_hex: str) -> None:
    emit("object_get", object_id=object_id_hex)


def get_flow_events(limit: int = 1000) -> list[dict]:
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    return global_worker.runtime._gcs.call(
        "InsightGet", {"limit": limit}, retries=3)


def build_call_graph(events: list[dict] | None = None) -> dict:
    """Aggregate events into {edges: {(caller, function): count},
    functions: {name: {calls, errors, total_s}}} for visualization."""
    if events is None:
        events = get_flow_events(limit=10000)
    edges: dict[tuple, int] = {}
    functions: dict[str, dict] = {}
    for ev in events:
        if ev["type"] == "call_submit":
            key = (ev.get("caller", "?"), ev["function"])
            edges[key] = edges.get(key, 0) + 1
        elif ev["type"] == "call_end":
            stats = functions.setdefault(
                ev["function"], {"calls": 0, "errors": 0, "total_s": 0.0})
            stats["calls"] += 1
            stats["errors"] += int(bool(ev.get("error")))
            stats["total_s"] += float(ev.get("duration_s", 0.0))
    return {
        "edges": [{"caller": c, "callee": f, "count": n}
                  for (c, f), n in sorted(edges.items())],
        "functions": functions,
    }
