"""Distributed FIFO queue backed by an actor
(ref: python/ray/util/queue.py)."""

from __future__ import annotations

import asyncio


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout=None):
        try:
            await asyncio.wait_for(self._queue.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full from None
        return True

    async def get(self, timeout=None):
        try:
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty from None

    async def put_nowait(self, item):
        try:
            self._queue.put_nowait(item)
        except asyncio.QueueFull:
            raise Full from None
        return True

    async def get_nowait(self):
        try:
            return self._queue.get_nowait()
        except asyncio.QueueEmpty:
            raise Empty from None

    async def qsize(self):
        return self._queue.qsize()

    async def empty(self):
        return self._queue.empty()

    async def full(self):
        return self._queue.full()


def _unwrap(call):
    """Re-raise the actor's Empty/Full as the local exception class
    (the framework wraps app errors in ActorError with a cause chain)."""
    try:
        return call()
    except Exception as e:  # noqa: BLE001
        cause = getattr(e, "cause", None)
        if isinstance(cause, Empty) or type(cause).__name__ == "Empty":
            raise Empty from None
        if isinstance(cause, Full) or type(cause).__name__ == "Full":
            raise Full from None
        raise


class Queue:
    """Driver/worker-shared queue; the payload lives in one actor, so
    producers and consumers anywhere in the cluster see one FIFO."""

    def __init__(self, maxsize: int = 0, *, actor_options: dict | None =
                 None):
        import ant_ray_tpu as art  # noqa: PLC0415

        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 16)
        opts.setdefault("num_cpus", 0)
        self._actor = art.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def put(self, item, block: bool = True, timeout: float | None = None):
        import ant_ray_tpu as art  # noqa: PLC0415

        if not block:
            return _unwrap(
                lambda: art.get(self._actor.put_nowait.remote(item)))
        return _unwrap(
            lambda: art.get(self._actor.put.remote(item, timeout)))

    def get(self, block: bool = True, timeout: float | None = None):
        import ant_ray_tpu as art  # noqa: PLC0415

        if not block:
            return _unwrap(
                lambda: art.get(self._actor.get_nowait.remote()))
        return _unwrap(lambda: art.get(
            self._actor.get.remote(timeout),
            timeout=None if timeout is None else timeout + 10))

    def qsize(self) -> int:
        import ant_ray_tpu as art  # noqa: PLC0415

        return art.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import ant_ray_tpu as art  # noqa: PLC0415

        return art.get(self._actor.empty.remote())

    def full(self) -> bool:
        import ant_ray_tpu as art  # noqa: PLC0415

        return art.get(self._actor.full.remote())

    def shutdown(self):
        import ant_ray_tpu as art  # noqa: PLC0415

        art.kill(self._actor)
