"""Deterministic chaos harness.

The stack grew its fault-injection knobs one at a time —
``testing_rpc_failure`` (seeded per-method RPC drops, protocol.py),
``testing_chunk_serve_delay_s`` / ``testing_chunk_truncate`` (bulk
transfer-channel faults, transfer.py), ``testing_preemption_notice``
(the file-based stand-in for the TPU maintenance-event API,
accelerators/tpu.py) — but nothing drove them: every resilience
scenario was hand-rolled per test.  This module unifies them behind one
seeded :class:`ChaosSchedule` (ref in spirit: src/ray/rpc/rpc_chaos.h +
the reference's chaos-testing release jobs):

* **knob faults** — build the ``_system_config`` dict once
  (``schedule.system_config()``) and hand it to ``init`` /
  ``Cluster(head_node_args={"_system_config": ...})``; every daemon in
  the cluster inherits the faults via the env-var channel.
* **scheduled actions** — ``at_step(n, fn)`` registers an action fired
  by a *logical* trigger (``schedule.fire(step)`` from the driver or
  the train loop): kill a worker/daemon at step N, inject a drain
  notice, drop a node.  Logical steps, not wall clock, keep runs
  reproducible — the same seed and the same step sequence replay the
  same fault schedule.
* **drain notices** — ``preemption_notice()`` creates (and
  ``trigger_preemption()`` later arms) the notice file the daemon's
  preemption watcher polls, standing in for a real maintenance event.

Typical test shape::

    chaos = ChaosSchedule(seed=7)
    chaos.chunk_serve_delay(0.01)
    cluster = Cluster(head_node_args={
        "_system_config": chaos.system_config()})
    chaos.at_step(3, lambda: cluster.remove_node(victim))
    ...
    for step in range(10):
        chaos.fire(step)       # deterministic kill at step 3
        ...

The ``chaos_schedule`` pytest fixture (import it from a conftest)
yields a fresh schedule and cleans its notice files up afterwards.
"""

from __future__ import annotations

import logging
import os
import random
import tempfile
import uuid
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


@dataclass(order=True)
class _Action:
    step: int
    order: int                      # registration order tie-break
    label: str = field(compare=False)
    fn: object = field(compare=False)
    fired: bool = field(default=False, compare=False)


class ChaosSchedule:
    """A seeded, deterministic fault schedule.

    Knob methods accumulate the ``_system_config`` overrides; action
    methods register step-triggered callbacks.  ``seed`` feeds both the
    RPC chaos injector (via ``testing_rpc_failure``'s seeded RNG) and
    this schedule's own RNG (``self.rng`` — use it for any randomized
    choice inside actions so replays stay identical)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self._rpc_failures: dict[str, float] = {}
        self._rpc_latency: dict[str, float] = {}
        self._config: dict = {}
        self._actions: list[_Action] = []
        self._notice_files: list[str] = []

    # ------------------------------------------------------ knob faults

    def rpc_failure(self, method: str, prob: float) -> "ChaosSchedule":
        """Drop ``method`` RPCs with probability ``prob`` (seeded —
        protocol._ChaosInjector; ref: rpc_chaos.h)."""
        self._rpc_failures[method] = prob
        return self

    def rpc_latency(self, method: str, seconds: float) -> "ChaosSchedule":
        """Inject ``seconds`` of client-side latency before every
        ``method`` RPC (testing_rpc_latency_s — protocol._ChaosInjector).
        The deterministic slow-replica / slow-network knob: e.g.
        ``rpc_latency("PushTask", 0.05)`` makes every actor call ride a
        congested link."""
        self._rpc_latency[method] = seconds
        return self

    def chunk_serve_delay(self, seconds: float) -> "ChaosSchedule":
        """Holder-side delay per served transfer chunk, so a holder can
        be killed mid-transfer deterministically."""
        self._config["testing_chunk_serve_delay_s"] = seconds
        return self

    def chunk_truncate(self, max_bytes: int) -> "ChaosSchedule":
        """Truncate bulk-channel chunk replies to ``max_bytes`` — torn
        transfers that exercise the stripe-failover path."""
        self._config["testing_chunk_truncate"] = max_bytes
        return self

    def preemption_notice(self, path: str | None = None) -> str:
        """Register a preemption-notice FILE (not yet armed): daemons
        configured with it poll for its existence.  Returns the path —
        call :meth:`trigger_preemption` (or create the file yourself)
        to fire the notice."""
        if path is None:
            path = os.path.join(
                tempfile.gettempdir(),
                f"art_chaos_notice_{uuid.uuid4().hex[:8]}")
        self._config["testing_preemption_notice"] = path
        self._notice_files.append(path)
        return path

    def trigger_preemption(self, deadline_s: float = 30.0,
                           reason: str = "chaos preemption") -> None:
        """Arm the registered notice file: every daemon polling it
        drains itself within one poll interval."""
        path = self._config.get("testing_preemption_notice")
        if not path:
            raise RuntimeError(
                "call preemption_notice() before trigger_preemption()")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{deadline_s} {reason}\n")
        os.rename(tmp, path)     # atomic: watchers never see a torn file

    def system_config(self) -> dict:
        """The unified ``_system_config`` dict for init/Cluster."""
        out = dict(self._config)
        if self._rpc_failures:
            # The leading seed entry carries the schedule's seed into
            # every daemon's _ChaosInjector — different seeds really do
            # produce different RPC fault sequences.
            out["testing_rpc_failure"] = ",".join(
                [f"seed:{self.seed}"]
                + [f"{m}:{p}"
                   for m, p in sorted(self._rpc_failures.items())])
        if self._rpc_latency:
            out["testing_rpc_latency_s"] = ",".join(
                f"{m}:{s}" for m, s in sorted(self._rpc_latency.items()))
        return out

    # ------------------------------------------------- scheduled actions

    def at_step(self, step: int, fn, label: str = "") -> "ChaosSchedule":
        """Register ``fn`` to run when :meth:`fire` first reaches
        ``step`` (kill a node, drain a daemon, flip a knob...)."""
        self._actions.append(_Action(
            step=step, order=len(self._actions),
            label=label or getattr(fn, "__name__", "action"), fn=fn))
        return self

    def kill_leader(self, step: int, cluster) -> "ChaosSchedule":
        """Schedule a GCS **leader kill** at logical step ``step`` —
        the control-plane-loss action for a replicated head
        (``Cluster(head_node_args={"gcs_standbys": N})``).  Resolves
        the leader at fire time (it may have moved since scheduling),
        SIGKILLs that replica, and leaves it dead: the cluster must
        fail over to a standby, not wait for a restart.  The killed
        address lands in :attr:`killed_leaders` for assertions."""
        self.killed_leaders: list[str] = getattr(
            self, "killed_leaders", [])

        def _kill():
            self.killed_leaders.append(cluster.kill_gcs_leader())

        return self.at_step(step, _kill, label="kill_leader")

    def fault_slice(self, step: int, slice_id: str, cluster,
                    label: str = "art-slice-id") -> "ChaosSchedule":
        """Schedule a **whole-slice failure** at logical step ``step``:
        SIGKILL every node daemon of one accelerator slice (nodes
        labeled ``label=slice_id``) in the same fire — the multi-slice
        failure domain, where a slice's power/DCN drops all its hosts
        as a unit and the training gang must drain and restart from the
        last checkpoint with zero steps lost.  Membership resolves at
        fire time (nodes may have joined since scheduling).  The killed
        addresses land in :attr:`killed_slices` for assertions."""
        self.killed_slices: dict[str, list[str]] = getattr(
            self, "killed_slices", {})

        def _kill():
            self.killed_slices[str(slice_id)] = cluster.kill_slice(
                slice_id, label=label)

        return self.at_step(step, _kill,
                            label=f"fault_slice:{slice_id}")

    def fire(self, step: int) -> list[str]:
        """Run every not-yet-fired action scheduled at or before
        ``step`` (deterministic order: step, then registration).
        Returns the labels fired — handy for test assertions."""
        fired = []
        for action in sorted(self._actions):
            if action.fired or action.step > step:
                continue
            action.fired = True
            logger.info("chaos: firing %r (scheduled step %d, now %d)",
                        action.label, action.step, step)
            action.fn()
            fired.append(action.label)
        return fired

    @property
    def pending(self) -> list[str]:
        return [a.label for a in sorted(self._actions) if not a.fired]

    # ------------------------------------------------------------ cleanup

    def cleanup(self) -> None:
        for path in self._notice_files:
            for p in (path, f"{path}.tmp"):
                try:
                    os.unlink(p)
                except OSError:
                    pass


def chaos_schedule_fixture():
    """Body of the ``chaos_schedule`` pytest fixture (kept import-safe
    for non-pytest consumers): yields a fresh schedule, cleans up its
    notice files afterwards."""
    schedule = ChaosSchedule(seed=0)
    try:
        yield schedule
    finally:
        schedule.cleanup()


try:  # pragma: no cover — exercised via tests' conftest import
    import pytest as _pytest

    chaos_schedule = _pytest.fixture(name="chaos_schedule")(
        chaos_schedule_fixture)
except ImportError:  # pragma: no cover
    chaos_schedule = None
