"""DAG nodes: lazily-bound task/actor-method call graphs.

Mirror of the reference's DAG surface (ref: python/ray/dag/dag_node.py +
compiled_dag_node.py:805): ``fn.bind(x)`` / ``actor.method.bind(x)``
build nodes, ``InputNode`` marks runtime inputs, ``node.execute(*args)``
submits the whole graph (dependencies flow as ObjectRefs, so independent
branches run in parallel and data moves through the object plane without
driver round-trips).  ``experimental_compile`` returns an executor that
pre-resolves the topology; true channel-based compiled execution (the
aDAG substrate — preallocated HBM/shm channels) is the planned upgrade
on this same API.
"""

from __future__ import annotations

from typing import Any


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # ---- traversal

    def _children(self):
        for value in list(self._bound_args) + list(
                self._bound_kwargs.values()):
            if isinstance(value, DAGNode):
                yield value

    def _topology(self) -> list["DAGNode"]:
        order: list[DAGNode] = []
        seen: set[int] = set()
        on_stack: set[int] = set()

        def visit(node: DAGNode):
            nid = id(node)
            if nid in on_stack:
                raise ValueError("cycle detected in DAG")
            if nid in seen:
                return
            on_stack.add(nid)
            for child in node._children():
                visit(child)
            on_stack.discard(nid)
            seen.add(nid)
            order.append(node)

        visit(self)
        return order

    # ---- execution

    def execute(self, *input_args, **input_kwargs):
        """Submit the graph; returns the ObjectRef of this (output) node."""
        resolved: dict[int, Any] = {}
        for node in self._topology():
            resolved[id(node)] = node._submit(resolved, input_args,
                                              input_kwargs)
        return resolved[id(self)]

    def experimental_compile(self, buffer_size_bytes: int = 8 << 20,
                             overlap: bool = True,
                             _force_interpreted: bool = False):
        """Compile to channel-connected per-actor exec loops (the aDAG
        substrate, dag/compiled.py).  ``overlap`` enables the per-actor
        read/compute overlap pass (ref: dag_node_operation.py op
        reordering).  Graphs that aren't pure actor-method pipelines —
        or hosts without the native channel extension — fall back to
        the interpreted pre-resolved executor."""
        if not _force_interpreted:
            from ant_ray_tpu._private.native import load_native  # noqa: PLC0415
            from ant_ray_tpu.dag.compiled import ChannelCompiledDAG  # noqa: PLC0415

            if load_native() is not None:
                try:
                    return ChannelCompiledDAG(self, buffer_size_bytes,
                                              overlap=overlap)
                except ValueError:
                    pass  # not an actor-only graph
        return CompiledDAG(self)

    def _materialize(self, value, resolved, input_args, input_kwargs):
        if isinstance(value, DAGNode):
            return resolved[id(value)]
        return value

    def _resolve_bound(self, resolved, input_args, input_kwargs):
        args = tuple(
            self._materialize(a, resolved, input_args, input_kwargs)
            for a in self._bound_args)
        kwargs = {
            k: self._materialize(v, resolved, input_args, input_kwargs)
            for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _submit(self, resolved, input_args, input_kwargs):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for a runtime input (ref: ray.dag.InputNode).

    Supports ``with InputNode() as inp:`` for API parity."""

    def __init__(self, index: int = 0):
        super().__init__((), {})
        self._index = index

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _submit(self, resolved, input_args, input_kwargs):
        if self._index >= len(input_args):
            raise ValueError(
                f"DAG executed with {len(input_args)} inputs but input "
                f"#{self._index} is bound")
        return input_args[self._index]


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._remote_function = remote_function

    def _submit(self, resolved, input_args, input_kwargs):
        args, kwargs = self._resolve_bound(resolved, input_args,
                                           input_kwargs)
        return self._remote_function.remote(*args, **kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, handle, method_name: str, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method_name = method_name

    def _submit(self, resolved, input_args, input_kwargs):
        args, kwargs = self._resolve_bound(resolved, input_args,
                                           input_kwargs)
        method = getattr(self._handle, self._method_name)
        return method.remote(*args, **kwargs)


class CompiledDAG:
    """Pre-resolved topology executor (ref: CompiledDAG.execute)."""

    def __init__(self, output: DAGNode):
        self._output = output
        self._order = output._topology()

    def execute(self, *input_args, **input_kwargs):
        resolved: dict[int, Any] = {}
        for node in self._order:
            resolved[id(node)] = node._submit(resolved, input_args,
                                              input_kwargs)
        return resolved[id(self._output)]

    def teardown(self):
        pass
