"""DAG layer: build static task/actor graphs with ``.bind()`` and execute
them (ref capability: ray.dag / compiled graphs, SURVEY §2.3 aDAG)."""

from ant_ray_tpu.dag.nodes import (
    ActorMethodNode,
    DAGNode,
    FunctionNode,
    InputNode,
)

__all__ = ["ActorMethodNode", "DAGNode", "FunctionNode", "InputNode"]
