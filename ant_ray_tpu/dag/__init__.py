"""DAG layer: build static task/actor graphs with ``.bind()`` and execute
them (ref capability: ray.dag / compiled graphs, SURVEY §2.3 aDAG).

``ant_ray_tpu.dag.collective`` binds collective ops (allreduce /
allgather / reducescatter) as DAG nodes executed by the participating
actors over their collective group."""

from ant_ray_tpu.dag import collective
from ant_ray_tpu.dag.nodes import (
    ActorMethodNode,
    DAGNode,
    FunctionNode,
    InputNode,
)

__all__ = ["ActorMethodNode", "DAGNode", "FunctionNode", "InputNode",
           "collective"]
