"""Channel-compiled DAG execution — the aDAG substrate.

Role of the reference's ``CompiledDAG``
(ref: python/ray/dag/compiled_dag_node.py:805): compile an actor-method
graph into per-actor EXECUTION LOOPS connected by preallocated mutable
shm channels (experimental/channel.py), so a steady-state step pays zero
task submissions — the driver writes the input channel, every stage
wakes on its input versions, and the result appears in the output
channel.  Backpressure is intrinsic: a writer cannot publish version
N+1 until all readers released N, which is exactly the microbatch
pipelining contract GPipe-style inter-actor PP needs.

TPU-first redesign notes: channels are plain mmap files with atomic
version counters (no plasma header dance, no NCCL channels — device
tensors ride the device-object path instead); the exec loop is a plain
actor task that never returns until teardown, so it composes with the
existing actor runtime (ordering, restarts, death detection) instead of
needing a separate executor class hierarchy.
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from ant_ray_tpu.experimental.channel import (
    ChannelClosedError,
    ChannelTimeoutError,
    ShmChannel,
    channel_dir,
)

EXEC_LOOP_METHOD = "__art_exec_loop__"


@dataclass
class ChannelSpec:
    path: str
    capacity: int
    num_readers: int


@dataclass
class StepSpec:
    """One actor-method call inside an exec loop.

    ``args``/``kwargs`` templates: ("const", v) | ("chan", idx) |
    ("input", input_index) | ("local", node_pos) — "chan" reads another
    actor's output channel, "local" reuses a value produced earlier in
    this same loop iteration (same-actor fusion: no channel, no copy).
    """

    method_name: str
    args: tuple
    kwargs: dict
    node_pos: int                      # position in the global topo order
    out_channel: int | None            # index into the program's channels


@dataclass
class ActorProgram:
    steps: list[StepSpec]
    # channel index -> spec; this actor opens only the ones its steps use
    channels: dict[int, ChannelSpec] = field(default_factory=dict)
    input_channel: int | None = None   # index of the driver input channel
    # Overlap pass (ref: dag_node_operation.py:325,576 — per-actor op
    # reordering that starts READs before COMPUTE): every channel the
    # tick will read is acquired+deserialized on prefetch threads at
    # tick start, so waits on one upstream overlap with deserializing
    # another and with this actor's own earlier compute steps.
    overlap: bool = True


class _PropagatedError:
    """An upstream step failed; carried as a value so the pipeline keeps
    flowing and the error reaches the driver through the output channel."""

    __slots__ = ("err",)

    def __init__(self, err: Exception):
        self.err = err


def exec_loop(actor_instance, program: ActorProgram) -> dict:
    """Runs inside the actor worker (dispatched by TaskExecutor when
    method_name == EXEC_LOOP_METHOD).  Opens this actor's channels, then
    loops: read inputs → run steps → write outputs, until any channel is
    closed by teardown."""
    opened: dict[int, ShmChannel] = {}
    for idx, spec in program.channels.items():
        opened[idx] = ShmChannel(spec.path, create=False)

    # Channels this program reads each tick (for the overlap prefetch).
    read_idxs: set[int] = set()
    for step in program.steps:
        for t in list(step.args) + list(step.kwargs.values()):
            if t[0] == "chan":
                read_idxs.add(t[1])
            elif t[0] == "input":
                read_idxs.add(t[1][0])
    pool = None
    if program.overlap and len(read_idxs) > 1:
        from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415

        pool = ThreadPoolExecutor(max_workers=len(read_idxs),
                                  thread_name_prefix="dag-read")

    iterations = 0
    try:
        while True:
            # One pipeline tick: values this iteration produced/read.
            local: dict[int, Any] = {}      # node_pos -> value
            chan_vals: dict[int, Any] = {}  # channel idx -> value
            reading: list[ShmChannel] = []
            prefetched: dict[int, Any] = {}
            if pool is not None:
                # Overlap pass: all reads in flight before any compute.
                for idx in read_idxs:
                    prefetched[idx] = pool.submit(
                        opened[idx].begin_read_tagged)

            def fetch_chan(idx: int):
                if idx not in chan_vals:
                    ch = opened[idx]
                    fut = prefetched.pop(idx, None)
                    tag, value = (fut.result() if fut is not None
                                  else ch.begin_read_tagged())
                    reading.append(ch)
                    chan_vals[idx] = (_PropagatedError(value)
                                      if tag == "error" else value)
                return chan_vals[idx]

            try:
                for step in program.steps:
                    try:
                        args = [_resolve(t, fetch_chan, local)
                                for t in step.args]
                        kwargs = {k: _resolve(t, fetch_chan, local)
                                  for k, t in step.kwargs.items()}
                        failed = next(
                            (a for a in args if
                             isinstance(a, _PropagatedError)), None
                        ) or next(
                            (v for v in kwargs.values()
                             if isinstance(v, _PropagatedError)), None)
                        if failed is not None:
                            result = failed
                        else:
                            method = getattr(actor_instance,
                                             step.method_name)
                            result = method(*args, **kwargs)
                    except Exception as e:  # noqa: BLE001 — propagated
                        result = _PropagatedError(e)
                    local[step.node_pos] = result
                    if step.out_channel is not None:
                        out = opened[step.out_channel]
                        if isinstance(result, _PropagatedError):
                            out.write_error(result.err)
                        else:
                            out.write(result)
            finally:
                for ch in reading:
                    ch.end_read()
            iterations += 1
    except ChannelClosedError:
        pass  # teardown
    finally:
        if pool is not None:
            pool.shutdown(wait=False)
        for ch in opened.values():
            ch.close()
    return {"iterations": iterations}


def _resolve(template, fetch_chan, local):
    kind, payload = template
    if kind == "const":
        return payload
    if kind == "chan":
        return fetch_chan(payload)
    if kind == "input":
        value = fetch_chan(payload[0])
        if isinstance(value, _PropagatedError):
            return value
        return value[payload[1]]
    if kind == "local":
        return local[payload]
    raise AssertionError(f"unknown template {kind}")


class CompiledDAGRef:
    """Handle to one in-flight compiled-DAG execution (ref:
    python/ray/experimental/compiled_dag_ref.py).  Results must be
    consumed in submission order — the output channel is a stream."""

    def __init__(self, dag: "ChannelCompiledDAG", version: int):
        self._dag = dag
        self._version = version
        self._value: Any = None
        self._done = False

    def get(self, timeout: float | None = None):
        if not self._done:
            self._dag._drain_until(self._version, timeout)
            self._value = self._dag._results.pop(self._version)
            self._done = True
        if isinstance(self._value, Exception):
            raise self._value
        return self._value


class ChannelCompiledDAG:
    """Driver-side compiled graph: creates the channels, starts the
    per-actor exec loops, and pumps input/output."""

    def __init__(self, output_node, buffer_size_bytes: int = 8 << 20,
                 overlap: bool = True):
        from ant_ray_tpu.dag.nodes import ActorMethodNode, InputNode

        self._buffer = buffer_size_bytes
        self._overlap = overlap
        self._output_node = output_node
        order = output_node._topology()
        self._order = order
        pos = {id(n): i for i, n in enumerate(order)}

        actor_nodes = [n for n in order
                       if isinstance(n, ActorMethodNode)]
        input_nodes = [n for n in order if isinstance(n, InputNode)]
        if not actor_nodes or len(actor_nodes) + len(input_nodes) != \
                len(order):
            raise ValueError(
                "channel compilation requires a graph of actor-method "
                "nodes (+ inputs); use .execute() for task graphs")

        # consumers[node_pos] = set of actor ids that read that node
        consumers: dict[int, set] = {}
        for n in actor_nodes:
            for child in n._children():
                consumers.setdefault(pos[id(child)], set()).add(
                    n._handle.actor_id)

        self._dir = os.path.join(channel_dir(),
                                 f"dag_{uuid.uuid4().hex[:10]}")
        os.makedirs(self._dir, exist_ok=True)
        self._channel_specs: dict[int, ChannelSpec] = {}

        def make_channel(tag: str, readers: int) -> int:
            idx = len(self._channel_specs)
            self._channel_specs[idx] = ChannelSpec(
                path=os.path.join(self._dir, f"{tag}_{idx}"),
                capacity=self._buffer, num_readers=readers)
            return idx

        # Input channel: read once per iteration by each actor that
        # consumes any InputNode.
        input_consumer_actors = set()
        for n in actor_nodes:
            if any(isinstance(c, InputNode) for c in n._children()):
                input_consumer_actors.add(n._handle.actor_id)
        if not input_consumer_actors:
            # Without an input channel the exec loops would free-run on
            # output backpressure alone, decoupled from execute() calls —
            # diverging from one-execution-per-execute semantics.  Such
            # graphs stay on the interpreted executor.
            raise ValueError(
                "channel compilation requires an InputNode feeding the "
                "graph")
        self._input_chan = make_channel("in", len(input_consumer_actors))

        # Output channels: one per node consumed by a DIFFERENT actor,
        # plus the final output (read by the driver).
        node_chan: dict[int, int] = {}
        for n in actor_nodes:
            p = pos[id(n)]
            other_actors = {a for a in consumers.get(p, set())
                            if a != n._handle.actor_id}
            readers = len(other_actors) + (1 if n is output_node else 0)
            if readers:
                node_chan[p] = make_channel(f"n{p}", readers)
        self._node_chan = node_chan

        # Per-actor programs, steps in topo order.
        programs: dict = {}
        order_of_actor: dict = {}
        for n in actor_nodes:
            aid = n._handle.actor_id
            prog = programs.get(aid)
            if prog is None:
                prog = ActorProgram(steps=[], overlap=self._overlap)
                programs[aid] = prog
                order_of_actor[aid] = n._handle
            p = pos[id(n)]
            args = tuple(self._template(a, pos, node_chan, aid)
                         for a in n._bound_args)
            kwargs = {k: self._template(v, pos, node_chan, aid)
                      for k, v in n._bound_kwargs.items()}
            prog.steps.append(StepSpec(
                method_name=n._method_name, args=args, kwargs=kwargs,
                node_pos=p, out_channel=node_chan.get(p)))

        # Wire channel specs into each program (only the ones it touches).
        for aid, prog in programs.items():
            used: set[int] = set()
            for step in prog.steps:
                if step.out_channel is not None:
                    used.add(step.out_channel)
                for t in list(step.args) + list(step.kwargs.values()):
                    if t[0] == "chan":
                        used.add(t[1])
                    elif t[0] == "input":
                        used.add(t[1][0])
            prog.channels = {i: self._channel_specs[i] for i in used}

        self._programs = programs
        self._handles = order_of_actor
        self._started = False
        self._loop_refs: list = []
        self._driver_in: ShmChannel | None = None
        self._driver_out: ShmChannel | None = None
        self._submitted = 0
        self._results: dict[int, Any] = {}
        self._drained = 0

    def _template(self, value, pos, node_chan, actor_id):
        from ant_ray_tpu.dag.nodes import (
            ActorMethodNode,
            DAGNode,
            InputNode,
        )

        if isinstance(value, InputNode):
            return ("input", (self._input_chan, value._index))
        if isinstance(value, ActorMethodNode):
            p = pos[id(value)]
            if value._handle.actor_id == actor_id:
                return ("local", p)       # same-actor fusion: no channel
            return ("chan", node_chan[p])
        if isinstance(value, DAGNode):
            raise ValueError("unsupported DAG node type in channel mode")
        return ("const", value)

    # ------------------------------------------------------------ start

    def _start(self):
        # Create every channel file up front (driver owns the files).
        self._creators = {
            idx: ShmChannel(spec.path, capacity=spec.capacity,
                            num_readers=spec.num_readers, create=True)
            for idx, spec in self._channel_specs.items()}
        if self._input_chan is not None:
            self._driver_in = self._creators[self._input_chan]
        out_pos = self._order.index(self._output_node)
        self._driver_out = self._creators[self._node_chan[out_pos]]

        from ant_ray_tpu.actor import ActorMethod

        for aid, prog in self._programs.items():
            handle = self._handles[aid]
            # Reserved method name, dispatched specially by the worker's
            # TaskExecutor (bypasses __getattr__'s public-name check).
            method = ActorMethod(handle, EXEC_LOOP_METHOD, 1)
            self._loop_refs.append(method.remote(prog))
        self._started = True

    # ------------------------------------------------------------ api

    def execute(self, *input_args):
        if getattr(self, "_closed", False):
            raise RuntimeError(
                "this compiled DAG was torn down; call "
                "experimental_compile() again for a fresh pipeline")
        if not self._started:
            self._start()
        self._submitted += 1
        if self._driver_in is not None:
            # The pipeline has a finite depth (one in-flight version per
            # channel).  When it is full, the input write blocks until a
            # stage releases — which can require the DRIVER to drain
            # finished results first (it is the output channel's reader).
            # So: poll results between short write attempts instead of
            # blocking forever (ref: CompiledDAG buffered results).
            while True:
                self._poll_results()
                try:
                    self._driver_in.write(tuple(input_args), timeout=0.05)
                    break
                except ChannelTimeoutError:
                    # A dead stage actor would stall the pipeline forever;
                    # surface it instead of spinning.
                    self._check_loops()
                    continue
        return CompiledDAGRef(self, self._submitted)

    def _check_loops(self):
        """Raise if any exec loop terminated while the DAG is live (actor
        death or an internal loop failure — either way the pipeline is
        wedged; the interpreted path surfaces the same as ActorDiedError)."""
        if not self._loop_refs:
            return
        import ant_ray_tpu as art  # noqa: PLC0415

        ready, _ = art.wait(self._loop_refs, num_returns=1, timeout=0.001)
        if not ready:
            return
        try:
            art.get(ready[0])
        except Exception as e:
            raise RuntimeError(
                f"compiled DAG wedged: an exec-loop actor died ({e})"
            ) from e
        raise RuntimeError(
            "compiled DAG wedged: an exec loop exited before teardown")

    def _poll_results(self):
        """Non-blocking drain of finished results into the buffer."""
        while self._drained < self._submitted:
            try:
                tag, value = self._driver_out.begin_read_tagged(timeout=0)
            except ChannelTimeoutError:
                return
            self._driver_out.end_read()
            self._drained += 1
            self._results[self._drained] = value

    def _drain_until(self, version: int, timeout: float | None):
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while self._drained < version:
            remaining = (0.2 if deadline is None else
                         min(0.2, max(0.001,
                                      deadline - time.monotonic())))
            try:
                tag, value = self._driver_out.begin_read_tagged(remaining)
            except ChannelTimeoutError:
                if deadline is not None and \
                        time.monotonic() >= deadline:
                    raise
                self._check_loops()  # dead actor ⇒ raise, don't hang
                continue
            self._driver_out.end_read()
            self._drained += 1
            self._results[self._drained] = value

    def teardown(self):
        if not self._started:
            return
        for ch in self._creators.values():
            ch.close()
        # Loops exit on ChannelClosedError and the actor replies arrive;
        # collect them so the actors are provably idle again.
        import ant_ray_tpu as art  # noqa: PLC0415

        try:
            art.wait(self._loop_refs, num_returns=len(self._loop_refs),
                     timeout=10)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        for spec in self._channel_specs.values():
            try:
                os.unlink(spec.path)
            except OSError:
                pass
        try:
            os.rmdir(self._dir)
        except OSError:
            pass
        self._started = False
        # Channels and loop actors are gone; the object is terminal.
        self._closed = True
