"""Collective operations as DAG nodes
(ref: python/ray/experimental/collective/operations.py:130-190 and
python/ray/dag/collective_node.py — ``allreduce.bind([n1, n2, ...])``
turns per-actor tensors into their elementwise reduction, executed BY
the actors over their collective group).

Semantics mirrored from the reference:

* inputs must be bound actor-method nodes on DISTINCT actors that
  already form a collective group (``create_collective_group``);
* ``bind`` returns one output node per input actor;
* executing ANY of the outputs triggers the whole group — a collective
  is all-or-nothing, so the group submits together (the reference
  schedules all peers' ops in the compiled schedule; here the shared
  ``_GroupBind`` submits every peer's op the first time any peer
  resolves, which keeps a single ``.execute()`` from deadlocking the
  rendezvous).

The op itself runs inside each actor's worker process via the
``__art_collective__`` execution hook, against the group state the
actor created with ``init_collective_group`` — on TPU meshes that is
the ``xla`` backend's ICI collectives, on CPU actors the gloo backend.
"""

from __future__ import annotations

from typing import Any

from ant_ray_tpu.dag.nodes import ActorMethodNode, DAGNode
from ant_ray_tpu.util.collective.types import ReduceOp


class CollectiveOutputNode(DAGNode):
    """The post-collective tensor on one participating actor."""

    def __init__(self, group_bind: "_GroupBind", index: int):
        super().__init__((), {})
        self._group_bind = group_bind
        self._index = index

    def _children(self):
        # Every peer's input is a dependency of every output: the graph
        # must pull ALL inputs in before any actor enters the collective
        # (a missing peer would hang the rendezvous forever).
        yield from self._group_bind.inputs

    def _submit(self, resolved: dict, input_args, input_kwargs):
        return self._group_bind.submit_all(resolved)[self._index]


class _GroupBind:
    """Shared state of one bound collective: inputs, verb, group."""

    def __init__(self, verb: str, inputs: list[ActorMethodNode],
                 group_name: str, op: ReduceOp):
        self.verb = verb
        self.inputs = list(inputs)
        self.group_name = group_name
        self.op = op
        handles = []
        for node in self.inputs:
            handle = getattr(node, "_handle", None)
            if handle is None:
                raise ValueError(
                    "collective inputs must be bound actor-method nodes "
                    f"(got {type(node).__name__})")
            handles.append(handle)
        if len({h.actor_id for h in handles}) != len(handles):
            raise ValueError(
                "collective inputs must live on distinct actors — the "
                "same actor cannot hold two ranks of one group")
        self.handles = handles

    def submit_all(self, resolved: dict) -> list:
        """Submit every peer's collective task once PER EXECUTION; the
        cache lives in the execution's ``resolved`` map (keyed by this
        bind), so re-executing the DAG re-runs the collective against
        the fresh input refs instead of returning stale results."""
        cached = resolved.get(id(self))
        if cached is not None:
            return cached
        from ant_ray_tpu._private.task_options import TaskOptions  # noqa: PLC0415
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        refs = []
        world = len(self.handles)
        for handle, node in zip(self.handles, self.inputs):
            tensor_ref = resolved[id(node)]
            refs.append(global_worker.submit_actor_task(
                handle, "__art_collective__",
                (self.verb, self.group_name, self.op.name, tensor_ref,
                 world),
                {}, TaskOptions()))
        resolved[id(self)] = refs
        return refs


class _CollectiveVerb:
    def __init__(self, verb: str):
        self._verb = verb

    def bind(self, input_nodes: list[ActorMethodNode], *,
             group_name: str = "default",
             op: ReduceOp = ReduceOp.SUM) -> list[CollectiveOutputNode]:
        if not input_nodes:
            raise ValueError("collective bind needs at least one input")
        group = _GroupBind(self._verb, input_nodes, group_name, op)
        return [CollectiveOutputNode(group, i)
                for i in range(len(input_nodes))]


#: ``allreduce.bind([...])`` — elementwise reduction across actors.
allreduce = _CollectiveVerb("allreduce")
#: ``allgather.bind([...])`` — every actor receives the concatenation.
allgather = _CollectiveVerb("allgather")
#: ``reducescatter.bind([...])`` — reduce then shard across actors.
reducescatter = _CollectiveVerb("reducescatter")


def execute_op(verb: str, group_name: str, op_name: str, tensor,
               bind_world: int | None = None) -> Any:
    """Worker-side execution hook (dispatched by the task executor for
    ``__art_collective__`` method calls)."""
    from ant_ray_tpu.util import collective as col  # noqa: PLC0415

    if bind_world is not None:
        actual = col.get_collective_group_size(group_name)
        if actual != bind_world:
            # Loud error beats the guaranteed rendezvous deadlock a
            # partial bind would otherwise hang in.
            raise ValueError(
                f"collective bound over {bind_world} actor(s) but group "
                f"{group_name!r} has world size {actual} — bind must "
                "cover every rank of the group")
    op = ReduceOp[op_name]
    if verb == "allreduce":
        return col.allreduce(tensor, group_name, op)
    if verb == "allgather":
        return col.allgather(tensor, group_name)
    if verb == "reducescatter":
        return col.reducescatter(tensor, group_name, op)
    raise ValueError(f"unknown collective verb {verb!r}")
