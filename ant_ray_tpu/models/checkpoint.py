"""Llama checkpoint loading — real weights into the functional param tree.

Capability mirror of the reference's checkpoint path (ref: the vLLM
engine loads HF checkpoints, llm/_internal/serve/engines/vllm/; the repo
previously only ever ran randomly-initialized params).  Supports the
HuggingFace Llama layout from a local directory:

* ``*.safetensors`` (preferred — zero-copy numpy views), else
* ``pytorch_model*.bin`` via torch (CPU), else
* a ``params.npz`` flat dump of our own tree (save_params/load_params).

HF stores linear weights as (out_features, in_features); this model
applies ``h @ W`` with (in, out), so every projection transposes on
load.  HF's q/k weights are already permuted for the rotate-half rope
convention, which is exactly ops/rope.py's layout — no re-permutation.
Weights load host-side as numpy and are placed on device (with whatever
sharding) by the caller, so a multi-host loader can shard-then-put.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import numpy as np

from ant_ray_tpu.models.llama import CONFIGS, LlamaConfig, param_shapes

_LAYER_RE = re.compile(r"model\.layers\.(\d+)\.(.+)")

# HF tensor name (per layer) → (our leaf name, transpose?)
_PER_LAYER = {
    "input_layernorm.weight": ("ln_attn", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "post_attention_layernorm.weight": ("ln_mlp", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}

_TOP_LEVEL = {
    "model.embed_tokens.weight": ("embed", False),
    "model.norm.weight": ("norm_f", False),
    "lm_head.weight": ("lm_head", True),
}


def config_from_hf(path: str) -> LlamaConfig:
    """Build a LlamaConfig from a HF ``config.json``."""
    with open(os.path.join(path, "config.json")) as f:
        cfg = json.load(f)
    if cfg.get("torch_dtype") in ("float32", "float64"):
        dtype: Any = np.float32
    else:  # bf16/f16 checkpoints compute in bf16 (the TPU dtype)
        import jax.numpy as jnp  # noqa: PLC0415

        dtype = jnp.bfloat16
    return LlamaConfig(
        dtype=dtype,
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads",
                           cfg["num_attention_heads"]),
        mlp_dim=cfg["intermediate_size"],
        max_seq=cfg.get("max_position_embeddings", 8192),
        rope_theta=float(cfg.get("rope_theta", 500000.0)),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(cfg.get("tie_word_embeddings", False)),
    )


def _iter_hf_tensors(path: str):
    """Yield (name, np.ndarray) from whatever weight files exist."""
    st_files = sorted(f for f in os.listdir(path)
                      if f.endswith(".safetensors"))
    if st_files:
        from safetensors import safe_open  # noqa: PLC0415

        for fname in st_files:
            with safe_open(os.path.join(path, fname), framework="np") as f:
                for name in f.keys():
                    yield name, f.get_tensor(name)
        return
    bin_files = sorted(f for f in os.listdir(path)
                       if f.startswith("pytorch_model")
                       and f.endswith(".bin"))
    if bin_files:
        import torch  # noqa: PLC0415

        for fname in bin_files:
            state = torch.load(os.path.join(path, fname),
                               map_location="cpu", weights_only=True)
            for name, tensor in state.items():
                yield name, tensor.float().numpy()
        return
    raise FileNotFoundError(
        f"no *.safetensors or pytorch_model*.bin under {path}")


def load_llama_params(path: str, config: LlamaConfig | None = None,
                      dtype: Any = None) -> tuple[dict, LlamaConfig]:
    """Load a HF-format Llama checkpoint directory into our param tree.

    Returns (params, config); ``params`` leaves are host numpy arrays in
    ``dtype`` (default: the config's dtype) — device placement/sharding
    is the caller's job (``jax.device_put(params, shardings)``)."""
    npz = os.path.join(path, "params.npz")
    if os.path.exists(npz):
        if config is None:
            raise ValueError("params.npz needs an explicit config")
        return load_params(npz, config), config

    if config is None:
        config = config_from_hf(path)
    shapes = param_shapes(config)
    out_dtype = dtype if dtype is not None else config.dtype
    layers: dict[str, list] = {
        name: [None] * config.n_layers
        for name in shapes["layers"]
    }
    top: dict[str, Any] = {}

    for name, tensor in _iter_hf_tensors(path):
        m = _LAYER_RE.match(name)
        if m:
            index, leaf_name = int(m.group(1)), m.group(2)
            entry = _PER_LAYER.get(leaf_name)
            if entry is None:
                continue  # rotary caches etc.
            ours, transpose = entry
            layers[ours][index] = (tensor.T if transpose else tensor)
        else:
            entry = _TOP_LEVEL.get(name)
            if entry is None:
                continue
            ours, transpose = entry
            top[ours] = tensor.T if transpose else tensor

    params: dict = {"layers": {}}
    for ours, per_layer in layers.items():
        missing = [i for i, t in enumerate(per_layer) if t is None]
        if missing:
            raise ValueError(
                f"checkpoint is missing layer tensors for "
                f"{ours!r}: layers {missing}")
        params["layers"][ours] = np.stack(per_layer).astype(out_dtype)
    for ours in ("embed", "norm_f"):
        if ours not in top:
            raise ValueError(f"checkpoint is missing {ours!r}")
        params[ours] = np.asarray(top[ours]).astype(out_dtype)
    if config.tie_embeddings:
        pass  # lm head is embed.T at use sites
    elif "lm_head" in top:
        params["lm_head"] = np.asarray(top["lm_head"]).astype(out_dtype)
    else:
        # Tied checkpoints sometimes omit lm_head with the flag unset.
        params["lm_head"] = params["embed"].T.copy()

    _check_shapes(params, shapes)
    return params, config


def _check_shapes(params: dict, shapes: dict) -> None:
    def walk(p, s, path):
        if isinstance(s, dict):
            for key, sub in s.items():
                if key not in p:
                    raise ValueError(f"missing param {path}/{key}")
                walk(p[key], sub, f"{path}/{key}")
        else:
            if tuple(p.shape) != tuple(s):
                raise ValueError(
                    f"shape mismatch at {path}: checkpoint "
                    f"{tuple(p.shape)} vs model {tuple(s)}")

    walk(params, shapes, "")


def save_params(params: dict, path: str,
                config: LlamaConfig | None = None) -> None:
    """Flat npz dump of our own tree (round-trip format for tests and
    single-host snapshots; training checkpoints use train/checkpoint).

    Pass ``config`` to stamp head-split metadata that load_params
    validates: projection shapes alone cannot distinguish head splits
    (16×64 and 8×128 heads both give a (dim, dim) wq), so a checkpoint
    loaded under the wrong split would otherwise silently scramble the
    head structure.
    """
    flat = {}
    if config is not None:
        flat["__head_split__"] = np.asarray(
            [config.n_heads, config.n_kv_heads, config.head_dim])

    def walk(tree, prefix):
        for key, value in tree.items():
            name = f"{prefix}{key}"
            if isinstance(value, dict):
                walk(value, name + ".")
            else:
                flat[name] = np.asarray(value)

    walk(params, "")
    np.savez(path, **flat)


def load_params(path: str, config: LlamaConfig) -> dict:
    data = np.load(path)
    params: dict = {}
    for name in data.files:
        if name == "__head_split__":
            saved = tuple(int(x) for x in data[name])
            want = (config.n_heads, config.n_kv_heads, config.head_dim)
            if saved != want:
                raise ValueError(
                    f"checkpoint head split (n_heads, n_kv_heads, "
                    f"head_dim)={saved} does not match the target "
                    f"config {want} — same tensor shapes, different "
                    "head structure; loading would scramble attention")
            continue
        parts = name.split(".")
        node = params
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = data[name]
    _check_shapes(params, param_shapes(config))
    return params


def resolve_model(model: str) -> tuple[dict | None, LlamaConfig]:
    """The engine-facing entry: a named config ("tiny", "llama3-8b")
    returns (None, config) — random init; a local checkpoint directory
    returns (loaded params, config-from-json)."""
    if model in CONFIGS:
        return None, CONFIGS[model]
    if os.path.isdir(model):
        return load_llama_params(model)
    raise ValueError(
        f"model {model!r} is neither a named config {sorted(CONFIGS)} "
        "nor a local checkpoint directory")
