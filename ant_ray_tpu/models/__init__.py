"""Model family implementations (functional JAX, sharding-rule driven)."""

from ant_ray_tpu.models import llama
from ant_ray_tpu.models.llama import LlamaConfig

__all__ = ["LlamaConfig", "llama"]
