"""Model family implementations (functional JAX, sharding-rule driven)."""

from ant_ray_tpu.models import gpt2, llama
from ant_ray_tpu.models.gpt2 import Gpt2Config
from ant_ray_tpu.models.llama import LlamaConfig

__all__ = ["Gpt2Config", "LlamaConfig", "gpt2", "llama"]
