"""Llama model family — functional JAX, one definition for every
parallelism strategy.

Design: parameters are a plain pytree with a parallel tree of *logical*
dimension names (parallel/sharding.py) so DP / FSDP / TP / SP placement is
a rule-table swap, not a model change.  Layers are stacked on a leading
axis and executed with ``lax.scan`` (fast compiles, uniform remat), blocks
are ``jax.checkpoint``-ed, attention dispatches to blockwise / pallas
flash / ring (sequence-parallel) based on the mesh.

Flagship configs mirror the reference's north-star benchmark target
(BASELINE.md: Llama-3-8B ≥ 40% MFU on v5e-64).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ant_ray_tpu.ops.attention import attention
from ant_ray_tpu.ops.rmsnorm import rmsnorm
from ant_ray_tpu.ops.rope import apply_rope, rope_frequencies
from ant_ray_tpu.parallel.sharding import logical_to_spec


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    # Mixture-of-experts MLP (0 = dense).  Experts shard over the mesh's
    # ``ep`` axis; routing is dense top-k dispatch (static shapes — the
    # XLA-friendly formulation; expert weights never leave their shard,
    # the combine einsum's contraction inserts the psum over ep).
    num_experts: int = 0
    experts_per_token: int = 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        p = self.vocab_size * self.dim                       # embed
        if self.num_experts:
            mlp = (self.dim * self.num_experts               # router
                   + 3 * self.num_experts * self.dim * self.mlp_dim)
        else:
            mlp = 3 * self.dim * self.mlp_dim                # gate, up, down
        per_layer = (
            self.dim * self.n_heads * self.head_dim          # wq
            + 2 * self.dim * self.n_kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * self.dim        # wo
            + mlp
            + 2 * self.dim                                   # norms
        )
        p += self.n_layers * per_layer + self.dim            # final norm
        if not self.tie_embeddings:
            p += self.dim * self.vocab_size                  # lm head
        return p


CONFIGS: dict[str, LlamaConfig] = {
    # ref parity: the Llama-3-8B benchmark model (BASELINE.md north star)
    "llama3-8b": LlamaConfig(),
    "llama3-1b": LlamaConfig(
        vocab_size=128256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        mlp_dim=8192, max_seq=8192),
    # small enough to train on one v5e chip (bench fallback).
    # head_dim=128 (not 64): the MXU contracts 128 lanes per pass, so
    # 64-deep attention matmuls run the array half-empty — measured 1.8×
    # slower end-to-end.  Matches Llama-3's head_dim at every scale.
    "llama-400m": LlamaConfig(
        vocab_size=32768, dim=1024, n_layers=24, n_heads=8, n_kv_heads=4,
        mlp_dim=4096, max_seq=4096),
    "tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_seq=512, dtype=jnp.float32),
    # MoE variant: 4 experts, top-2 routing — the ep-axis test model
    "moe-tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_seq=512, dtype=jnp.float32,
        num_experts=4, experts_per_token=2),
}


# ---------------------------------------------------------------- params

def param_shapes(config: LlamaConfig) -> dict:
    c = config
    hd = c.head_dim
    if c.num_experts:
        mlp_shapes = {
            "router": (c.n_layers, c.dim, c.num_experts),
            "w_gate": (c.n_layers, c.num_experts, c.dim, c.mlp_dim),
            "w_up": (c.n_layers, c.num_experts, c.dim, c.mlp_dim),
            "w_down": (c.n_layers, c.num_experts, c.mlp_dim, c.dim),
        }
    else:
        mlp_shapes = {
            "w_gate": (c.n_layers, c.dim, c.mlp_dim),
            "w_up": (c.n_layers, c.dim, c.mlp_dim),
            "w_down": (c.n_layers, c.mlp_dim, c.dim),
        }
    return {
        "embed": (c.vocab_size, c.dim),
        "layers": {
            "ln_attn": (c.n_layers, c.dim),
            "wq": (c.n_layers, c.dim, c.n_heads * hd),
            "wk": (c.n_layers, c.dim, c.n_kv_heads * hd),
            "wv": (c.n_layers, c.dim, c.n_kv_heads * hd),
            "wo": (c.n_layers, c.n_heads * hd, c.dim),
            "ln_mlp": (c.n_layers, c.dim),
            **mlp_shapes,
        },
        "norm_f": (c.dim,),
        **({} if config.tie_embeddings else
           {"lm_head": (c.dim, c.vocab_size)}),
    }


def param_logical_dims(config: LlamaConfig) -> dict:
    """Logical dim names per param (see parallel/sharding.py rules)."""
    if config.num_experts:
        mlp_dims = {
            "router": (None, None, "experts"),
            "w_gate": (None, "experts", "embed_param", "mlp"),
            "w_up": (None, "experts", "embed_param", "mlp"),
            "w_down": (None, "experts", "mlp", "embed_param"),
        }
    else:
        mlp_dims = {
            "w_gate": (None, "embed_param", "mlp"),
            "w_up": (None, "embed_param", "mlp"),
            "w_down": (None, "mlp", "embed_param"),
        }
    tree = {
        "embed": ("vocab", "embed_param"),
        "layers": {
            "ln_attn": (None, "norm"),
            "wq": (None, "embed_param", "heads_flat"),
            "wk": (None, "embed_param", "heads_flat"),
            "wv": (None, "embed_param", "heads_flat"),
            "wo": (None, "heads_flat", "embed_param"),
            "ln_mlp": (None, "norm"),
            **mlp_dims,
        },
        "norm_f": ("norm",),
    }
    if not config.tie_embeddings:
        tree["lm_head"] = ("embed_param", "vocab")
    return tree


# extra rule: flattened (heads*head_dim) dims shard over tp
LLAMA_RULES_EXTRA = {"heads_flat": "tp"}


def llama_rules() -> dict:
    from ant_ray_tpu.parallel.sharding import DEFAULT_LLAMA_RULES  # noqa: PLC0415

    rules = dict(DEFAULT_LLAMA_RULES)
    rules.update(LLAMA_RULES_EXTRA)
    return rules


def init_params(config: LlamaConfig, key) -> dict:
    shapes = param_shapes(config)
    flat, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(
        x, tuple))
    keys = jax.random.split(key, len(flat))

    def _init(shape, k):
        if len(shape) <= 2 and shape[-1] == config.dim and len(shape) == 1:
            return jnp.ones(shape, config.dtype)             # final norm
        if shape[-1] == config.dim and len(shape) == 2 and \
                shape[0] == config.n_layers:
            return jnp.ones(shape, config.dtype)             # layer norms
        scale = 0.02
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(
            config.dtype)

    leaves = [_init(s, k) for s, k in zip(flat, keys)]
    return jax.tree.unflatten(treedef, leaves)


def param_shardings(config: LlamaConfig, mesh) -> dict:
    """NamedSharding pytree for jit in_shardings / device_put."""
    from jax.sharding import NamedSharding  # noqa: PLC0415

    rules = llama_rules()
    logical = param_logical_dims(config)
    shapes = param_shapes(config)

    def _shard(dims, _shape):
        return NamedSharding(mesh, logical_to_spec(dims, rules))

    return jax.tree.map(
        _shard, logical, shapes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(d, (str, type(None))) for d in x))


# ---------------------------------------------------------------- forward

def apply_block(layer: dict, x, c: LlamaConfig, cos, sin, positions,
                attend, constrain_act, *, return_kv: bool = False):
    """One transformer block — shared by the scan path (forward), the
    GPipe stage path (loss_fn_pp), and decode variants."""
    batch, seq, _ = x.shape
    h = rmsnorm(x, layer["ln_attn"], c.norm_eps)
    xq = (h @ layer["wq"]).reshape(batch, seq, c.n_heads, c.head_dim)
    xk = (h @ layer["wk"]).reshape(batch, seq, c.n_kv_heads, c.head_dim)
    xv = (h @ layer["wv"]).reshape(batch, seq, c.n_kv_heads, c.head_dim)
    xq = apply_rope(xq, cos, sin, positions)
    xk = apply_rope(xk, cos, sin, positions)
    xq = constrain_act(xq, ("batch", "seq", "heads", "head_dim"))
    xk = constrain_act(xk, ("batch", "seq", "kv_heads", "head_dim"))
    attn = attend(xq, xk, xv)
    attn = attn.reshape(batch, seq, c.n_heads * c.head_dim)
    x = x + (attn @ layer["wo"]).astype(x.dtype)
    x = constrain_act(x, ("batch", "seq", "embed"))

    h = rmsnorm(x, layer["ln_mlp"], c.norm_eps)
    if c.num_experts:
        x = x + _moe_mlp(layer, h, c, constrain_act).astype(x.dtype)
    else:
        gated = jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])
        x = x + (gated @ layer["w_down"]).astype(x.dtype)
    x = constrain_act(x, ("batch", "seq", "embed"))
    kv = (xk.astype(c.dtype), xv.astype(c.dtype)) if return_kv else None
    return x, kv


def _moe_mlp(layer: dict, h, c: LlamaConfig, constrain_act):
    """Top-k mixture-of-experts MLP with dense dispatch.

    Every expert runs on every token with static shapes (XLA-friendly; no
    ragged gather), weighted by the router's top-k gates.  The experts
    dimension shards over the mesh's ``ep`` axis — expert weights stay on
    their shard and the final combine einsum (contraction over e) is
    where XLA inserts the psum across ep.
    """
    router_logits = h @ layer["router"]                    # (b, s, E)
    top_vals, top_idx = lax.top_k(router_logits, c.experts_per_token)
    gates = jax.nn.softmax(top_vals, axis=-1)              # (b, s, k)
    # Scatter the top-k gates back to a dense (b, s, E) weight map.
    weights = jnp.sum(
        jax.nn.one_hot(top_idx, c.num_experts, dtype=h.dtype)
        * gates[..., None].astype(h.dtype), axis=-2)
    ge = jnp.einsum("bsd,edm->ebsm", h, layer["w_gate"])   # (E, b, s, m)
    ue = jnp.einsum("bsd,edm->ebsm", h, layer["w_up"])
    oe = jnp.einsum("ebsm,emd->ebsd", jax.nn.silu(ge) * ue,
                    layer["w_down"])
    oe = constrain_act(oe, ("experts", "batch", "seq", "embed"))
    return jnp.einsum("ebsd,bse->bsd", oe, weights)


def forward(params: dict, tokens, config: LlamaConfig, *, mesh=None,
            attn_impl: str = "auto", positions=None,
            return_kv: bool = False, logits_at=None,
            remat: str = "full"):
    """tokens: (batch, seq) int32 → logits (batch, seq, vocab) fp32.

    When ``mesh`` is provided, activations get sharding constraints
    (batch over dp/fsdp, seq over sp, heads over tp) and sequence-sharded
    meshes use ring attention.

    ``return_kv=True`` additionally returns the per-layer K/V
    (layers, b, s, kv_heads, hd) for cache insertion (serving prefill);
    ``logits_at`` (traced scalar position) computes logits for that one
    position only — (b, vocab) — skipping the full-sequence lm-head
    matmul.

    ``remat`` trades HBM for recompute FLOPs in the backward pass:
    "full" (checkpoint every block — the multi-chip/8B default), "dots"
    (save matmul outputs, recompute the cheap elementwise tail), "none"
    (save everything — best MFU when the model fits, e.g. the single-chip
    bench).
    """
    c = config
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta,
                                jnp.float32)
    use_ring = mesh is not None and mesh.shape.get("sp", 1) > 1

    def constrain_act(x, dims):
        if mesh is None:
            return x
        from jax.sharding import NamedSharding  # noqa: PLC0415

        spec = logical_to_spec(dims, llama_rules())
        return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    def attend(xq, xk, xv):
        if use_ring:
            from ant_ray_tpu.parallel.ring import ring_attention  # noqa: PLC0415

            return ring_attention(xq, xk, xv, mesh=mesh, causal=True)
        return attention(xq, xk, xv, causal=True, impl=attn_impl)

    def block(x, layer):
        return apply_block(layer, x, c, cos, sin, positions, attend,
                           constrain_act, return_kv=return_kv)

    if remat == "full":
        block = jax.checkpoint(block)
    elif remat == "dots":
        block = jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif remat == "matmuls":
        # Saves every matmul output (batch dims included) plus the flash
        # kernel's named residuals (attention output + logsumexp) — in a
        # transformer block that is all the expensive ops, so backward
        # recomputes only the elementwise tail and never re-runs the
        # attention forward.  ~3× the activation HBM of "full",
        # near-"none" step time; the single-chip bench sweet spot when
        # "none" OOMs.
        from ant_ray_tpu.ops.attention import saveable_attention_policy  # noqa: PLC0415

        block = jax.checkpoint(block, policy=saveable_attention_policy())
    elif remat != "none":
        raise ValueError(f"unknown remat policy {remat!r}")

    x = params["embed"][tokens].astype(c.dtype)
    # Staged reshard: first acknowledge the gather's TABLE-natural
    # output sharding (embed dim carries the table's fsdp shards; batch
    # keeps its dp shard — fsdp moves from batch to embed for one hop),
    # then relayout to the activation spec.  One constraint straight to
    # the target makes SPMD fall back to "involuntary full
    # rematerialization" (replicate-everything) on the sp/tp meshes;
    # the explicit intermediate lets it emit a plain all-gather +
    # dynamic-slice.  Spec built directly: the logical rule table can't
    # say "batch over dp only".
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec  # noqa: PLC0415

        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, PartitionSpec("dp", "sp", "fsdp")))
    x = constrain_act(x, ("batch", "seq", "embed"))
    x, kv = lax.scan(block, x, params["layers"])
    x = rmsnorm(x, params["norm_f"], c.norm_eps)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"])
    if logits_at is not None:
        x = jnp.take(x, logits_at, axis=1)          # (b, dim)
        logits = (x @ head.astype(c.dtype)).astype(jnp.float32)
    else:
        logits = (x @ head.astype(c.dtype)).astype(jnp.float32)
        logits = constrain_act(logits, ("batch", "seq", None))
    if return_kv:
        return logits, kv[0], kv[1]
    return logits


def loss_fn(params: dict, batch: dict, config: LlamaConfig, *, mesh=None,
            attn_impl: str = "auto", remat: str = "full"):
    """batch: {"tokens": (b, s+1) int32} — next-token cross entropy."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, config, mesh=mesh, attn_impl=attn_impl,
                     remat=remat)
    import optax  # noqa: PLC0415

    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:]
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(losses)


def loss_fn_pp(params: dict, batch: dict, config: LlamaConfig, *, mesh,
               num_microbatches: int = 4, attn_impl: str = "auto"):
    """Pipeline-parallel next-token loss: the transformer blocks run as a
    GPipe schedule over the mesh's ``pp`` axis (parallel/pipeline.py —
    single compiled program, activations hop stages via ppermute),
    composing with dp/fsdp/tp on the remaining axes.  Requires
    n_layers % pp == 0 and batch % num_microbatches == 0."""
    from ant_ray_tpu.parallel.pipeline import gpipe  # noqa: PLC0415

    c = config
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    pp = mesh.shape["pp"]
    if c.n_layers % pp != 0:
        raise ValueError(f"n_layers {c.n_layers} % pp {pp} != 0")
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta,
                                jnp.float32)

    def attend(xq, xk, xv):
        return attention(xq, xk, xv, causal=True, impl=attn_impl)

    def no_constrain(x, _dims):
        return x

    def stage_fn(stage_layers, mx):
        def body(h, layer):
            h, _ = apply_block(layer, h, c, cos, sin, None, attend,
                               no_constrain)
            return h, None

        out, _ = lax.scan(body, mx, stage_layers)
        return out

    x = params["embed"][inputs].astype(c.dtype)          # (b, s, d)
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f"batch {b} % microbatches {num_microbatches} != 0")
    micro = x.reshape(num_microbatches, b // num_microbatches,
                     *x.shape[1:])
    stacked = jax.tree.map(
        lambda p: p.reshape(pp, c.n_layers // pp, *p.shape[1:]),
        params["layers"])
    y = gpipe(stage_fn, stacked, micro, mesh=mesh)
    x = y.reshape(b, *y.shape[2:])
    x = rmsnorm(x, params["norm_f"], c.norm_eps)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"])
    logits = (x @ head.astype(c.dtype)).astype(jnp.float32)
    import optax  # noqa: PLC0415

    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, targets))


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token (6·N matmul + attention quadratic term)."""
    c = config
    matmul = 6 * c.num_params()
    attn = 12 * c.n_layers * c.head_dim * c.n_heads * seq_len
    return matmul + attn


# ------------------------------------------------------------- kv cache
# Serving-path primitives (ref capability: llm/_internal/serve/engines/
# vllm — re-designed TPU-first: dense per-slot KV slabs with static
# shapes instead of paged indirection, because XLA wants static shapes
# and HBM slabs keep the decode matmuls MXU-friendly).

def init_kv_cache(config: LlamaConfig, slots: int,
                  max_seq: int | None = None) -> dict:
    """Per-slot dense KV slabs: (layers, slots, max_seq, kv_heads, hd)."""
    c = config
    ms = max_seq or c.max_seq
    shape = (c.n_layers, slots, ms, c.n_kv_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
        # tokens already written per slot (== next write position)
        "length": jnp.zeros((slots,), jnp.int32),
    }


def prefill_into_cache(params: dict, tokens, cache: dict, slot,
                       length, config: LlamaConfig, *, mesh=None):
    """Run prefill on one padded prompt (1, s) and write its K/V into
    ``slot``; returns (last-token logits (vocab,), new cache).

    ``slot`` and ``length`` may be traced (one compile per prompt
    bucket, none per slot); logits are computed for the last real token
    only — the padded tail writes garbage K/V that decode masks (and
    later overwrites)."""
    last_pos = jnp.maximum(length - 1, 0)
    logits, ks, vs = forward(params, tokens, config, mesh=mesh,
                             return_kv=True, logits_at=last_pos)
    cache = dict(cache)
    slot = jnp.asarray(slot, jnp.int32)
    cache["k"] = lax.dynamic_update_slice(
        cache["k"], ks, (0, slot, 0, 0, 0))
    cache["v"] = lax.dynamic_update_slice(
        cache["v"], vs, (0, slot, 0, 0, 0))
    cache["length"] = cache["length"].at[slot].set(length)
    return logits[0], cache


def prefill_chunk_into_cache(params: dict, tokens, cache: dict, slot,
                             start, chunk_len, config: LlamaConfig):
    """Ingest ONE fixed-size chunk of a prompt into ``slot``.

    tokens: (chunk,) int32 — ``chunk_len`` real tokens, zero-padded to
    the engine's fixed chunk width.  ``slot``, ``start`` (absolute
    offset of the chunk in the slab) and ``chunk_len`` are all traced
    scalars, so a single compiled variant covers every chunk of every
    prompt — the chunked-prefill replacement for the O(log max_seq)
    bucketed `prefill_into_cache` variants.

    Chunk queries attend against the slot's FULL slab (earlier chunks'
    K/V plus this chunk's own, causally masked), mirroring
    `decode_step`'s masked-slab attention so the dense-slab static-shape
    discipline holds.  Pad positions write nothing: their scatter
    indices are pushed out of bounds and dropped, and the returned
    logits are taken at the chunk's last REAL token.

    Returns (logits (vocab,) fp32, new cache with slot length set to
    ``start + chunk_len``).
    """
    c = config
    chunk = tokens.shape[0]
    max_seq = cache["k"].shape[2]
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta,
                                jnp.float32)
    group = c.n_heads // c.n_kv_heads
    slot = jnp.asarray(slot, jnp.int32)
    start = jnp.asarray(start, jnp.int32)
    chunk_len = jnp.asarray(chunk_len, jnp.int32)
    offs = jnp.arange(chunk, dtype=jnp.int32)
    pos = start + offs                           # (chunk,) absolute
    real = offs < chunk_len                      # pad mask
    # Pad tokens' writes land at max_seq → dropped by the scatter; rope
    # positions are clamped only to keep the gather in range (their
    # values never reach the slab or the masked attention).
    write_pos = jnp.where(real, pos, jnp.int32(max_seq))
    rope_pos = jnp.minimum(pos, jnp.int32(c.max_seq - 1))
    pc = cos[rope_pos][:, None, :]               # (chunk, 1, hd/2)
    ps = sin[rope_pos][:, None, :]

    def block(x, scanned):
        layer, ck_all, cv_all = scanned          # (slots, ms, kvh, hd)
        h = rmsnorm(x, layer["ln_attn"], c.norm_eps)
        xq = (h @ layer["wq"]).reshape(chunk, c.n_heads, c.head_dim)
        xk = (h @ layer["wk"]).reshape(chunk, c.n_kv_heads, c.head_dim)
        xv = (h @ layer["wv"]).reshape(chunk, c.n_kv_heads, c.head_dim)
        xq = _rope_one(xq, pc, ps)
        xk = _rope_one(xk, pc, ps)
        ck = lax.dynamic_index_in_dim(ck_all, slot, axis=0,
                                      keepdims=False)  # (ms, kvh, hd)
        cv = lax.dynamic_index_in_dim(cv_all, slot, axis=0,
                                      keepdims=False)
        ck = ck.at[write_pos].set(xk.astype(ck.dtype))
        cv = cv.at[write_pos].set(xv.astype(cv.dtype))
        q = xq.reshape(chunk, c.n_kv_heads, group, c.head_dim)
        scores = jnp.einsum("ckgd,tkd->ckgt", q, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(c.head_dim))
        valid = jnp.arange(max_seq)[None, :] <= pos[:, None]  # (chunk, ms)
        scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("ckgt,tkd->ckgd", probs.astype(ck.dtype), cv,
                         preferred_element_type=jnp.float32)
        out = out.reshape(chunk, c.n_heads * c.head_dim).astype(x.dtype)
        x = x + (out @ layer["wo"]).astype(x.dtype)
        h = rmsnorm(x, layer["ln_mlp"], c.norm_eps)
        gated = jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])
        x = x + (gated @ layer["w_down"]).astype(x.dtype)
        ck_all = lax.dynamic_update_slice(ck_all, ck[None],
                                          (slot, 0, 0, 0))
        cv_all = lax.dynamic_update_slice(cv_all, cv[None],
                                          (slot, 0, 0, 0))
        return x, (ck_all, cv_all)

    x = params["embed"][tokens].astype(c.dtype)  # (chunk, dim)
    x, (new_k, new_v) = lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["norm_f"], c.norm_eps)
    x_last = jnp.take(x, jnp.maximum(chunk_len - 1, 0), axis=0)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"])
    logits = (x_last @ head.astype(c.dtype)).astype(jnp.float32)
    cache = {"k": new_k, "v": new_v,
             "length": cache["length"].at[slot].set(start + chunk_len)}
    return logits, cache


def decode_step(params: dict, last_tokens, cache: dict,
                config: LlamaConfig, active=None):
    """One token for every slot, attending against the KV cache.

    last_tokens: (slots,) int32 — the most recent token per slot.
    ``active`` ((slots,) bool, optional): slots marked False neither
    write K/V nor advance their length — required once idle slots can
    hold a RESIDENT session's slab (session KV must stay bit-exact
    while the slot sits out decode steps).  ``active=None`` keeps the
    legacy everything-steps behavior.
    Returns (logits (slots, vocab) fp32, new cache with +1 lengths).
    """
    c = config
    slots = last_tokens.shape[0]
    max_seq = cache["k"].shape[2]
    pos = cache["length"]                       # (slots,) write position
    if active is not None:
        # Inactive slots' scatter writes are pushed out of bounds (and
        # dropped); their lengths hold still below.
        write_pos = jnp.where(active, pos, jnp.int32(max_seq))
    else:
        write_pos = pos
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta,
                                jnp.float32)
    group = c.n_heads // c.n_kv_heads

    def block(x, scanned):
        layer, ck, cv = scanned                 # ck/cv: (slots, ms, kvh, hd)
        h = rmsnorm(x, layer["ln_attn"], c.norm_eps)
        xq = (h @ layer["wq"]).reshape(slots, c.n_heads, c.head_dim)
        xk = (h @ layer["wk"]).reshape(slots, c.n_kv_heads, c.head_dim)
        xv = (h @ layer["wv"]).reshape(slots, c.n_kv_heads, c.head_dim)
        # rope at each slot's own position
        pc = cos[pos][:, None, :]               # (slots, 1, hd/2)
        ps = sin[pos][:, None, :]
        xq = _rope_one(xq, pc, ps)
        xk = _rope_one(xk, pc, ps)
        ck = ck.at[jnp.arange(slots), write_pos].set(xk.astype(ck.dtype))
        cv = cv.at[jnp.arange(slots), write_pos].set(xv.astype(cv.dtype))
        # GQA attention against the slab, masked beyond each length.
        # bf16 inputs with fp32 accumulation keep the matmuls at full
        # MXU rate without an fp32 copy of the slab (see ops/attention).
        q = xq.reshape(slots, c.n_kv_heads, group, c.head_dim)
        scores = jnp.einsum("skgd,stkd->skgt", q, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(c.head_dim))
        valid = jnp.arange(max_seq)[None, :] <= pos[:, None]  # (slots, ms)
        scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("skgt,stkd->skgd", probs.astype(ck.dtype), cv,
                         preferred_element_type=jnp.float32)
        out = out.reshape(slots, c.n_heads * c.head_dim).astype(x.dtype)
        x = x + (out @ layer["wo"]).astype(x.dtype)
        h = rmsnorm(x, layer["ln_mlp"], c.norm_eps)
        gated = jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])
        x = x + (gated @ layer["w_down"]).astype(x.dtype)
        return x, (ck, cv)

    x = params["embed"][last_tokens].astype(c.dtype)   # (slots, dim)
    x, (new_k, new_v) = lax.scan(
        block, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["norm_f"], c.norm_eps)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"])
    logits = (x @ head.astype(c.dtype)).astype(jnp.float32)
    # Clamp so idle slots (which keep stepping) never index past the
    # slab; their scatter writes drop out of bounds harmlessly.  With an
    # ``active`` mask, inactive slots' lengths hold perfectly still so a
    # resident session's slab stays byte-stable across steps.
    new_len = jnp.minimum(cache["length"] + 1, jnp.int32(max_seq))
    if active is not None:
        new_len = jnp.where(active, new_len, cache["length"])
    cache = {"k": new_k, "v": new_v, "length": new_len}
    return logits, cache


def _rope_one(x, cos, sin):
    """Rotate (slots, heads, hd) at per-slot positions (cos/sin already
    gathered: (slots, 1, hd/2))."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    xf1 = x1.astype(jnp.float32)
    xf2 = x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
        axis=-1).astype(x.dtype)


# ---------------------------------------------------------------- generate

def greedy_generate(params: dict, config: LlamaConfig, prompt,
                    max_new_tokens: int = 32):
    """Minimal greedy decoding (no KV cache — correctness utility; the
    serving engine owns the fast path)."""
    tokens = jnp.asarray(prompt)[None] if jnp.ndim(prompt) == 1 else prompt

    @jax.jit
    def next_token(toks):
        logits = forward(params, toks, config)
        return jnp.argmax(logits[:, -1], axis=-1)

    for _ in range(max_new_tokens):
        nxt = next_token(tokens)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens
