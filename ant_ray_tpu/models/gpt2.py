"""GPT-2 model family — functional JAX, second model family next to
Llama (ref: the reference serves arbitrary HF model families through
its vLLM engines; here the engine-facing contract is the same
functional shape as models/llama.py — config dataclass, param pytree +
logical dims, ``forward``/``loss_fn`` — so Train/Serve/LLM layers work
with either family unchanged).

Architecture (GPT-2): learned positional embeddings, pre-LayerNorm
blocks, fused-qkv multi-head attention, GELU MLP (4x), tied LM head.
``from_hf_state_dict`` converts a HuggingFace ``GPT2LMHeadModel``
state dict (Conv1D convention: weights stored (in, out)) so real
checkpoints load; numerical parity vs the HF torch implementation is
pinned by tests/test_gpt2.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ant_ray_tpu.ops.attention import attention


@dataclasses.dataclass(frozen=True)
class Gpt2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    norm_eps: float = 1e-5
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def mlp_dim(self) -> int:
        return 4 * self.dim

    def num_params(self) -> int:
        per_layer = (12 * self.dim * self.dim  # qkv + proj + mlp
                     + 13 * self.dim)          # biases + LN params
        return (self.vocab_size * self.dim + self.n_positions * self.dim
                + self.n_layers * per_layer + 2 * self.dim)


CONFIGS = {
    "gpt2": Gpt2Config(),
    "gpt2-medium": Gpt2Config(dim=1024, n_layers=24, n_heads=16),
    "gpt2-large": Gpt2Config(dim=1280, n_layers=36, n_heads=20),
    "tiny": Gpt2Config(vocab_size=257, n_positions=128, dim=64,
                       n_layers=2, n_heads=4),
}


def param_shapes(config: Gpt2Config) -> dict:
    d, L = config.dim, config.n_layers
    return {
        "wte": (config.vocab_size, d),
        "wpe": (config.n_positions, d),
        "layers": {
            # stacked on the leading axis, executed with lax.scan
            "ln1_g": (L, d), "ln1_b": (L, d),
            "qkv_w": (L, d, 3 * d), "qkv_b": (L, 3 * d),
            "proj_w": (L, d, d), "proj_b": (L, d),
            "ln2_g": (L, d), "ln2_b": (L, d),
            "fc_w": (L, d, config.mlp_dim), "fc_b": (L, config.mlp_dim),
            "out_w": (L, config.mlp_dim, d), "out_b": (L, d),
        },
        "lnf_g": (d,), "lnf_b": (d,),
    }


def param_logical_dims(config: Gpt2Config) -> dict:
    """Logical axis names per parameter (see parallel/sharding.py):
    TP splits attention heads and the MLP hidden dim; FSDP shards the
    embedding/model dim."""
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "layers": {
            "ln1_g": ("layer", None), "ln1_b": ("layer", None),
            "qkv_w": ("layer", "embed", "heads"),
            "qkv_b": ("layer", "heads"),
            "proj_w": ("layer", "heads", "embed"),
            "proj_b": ("layer", None),
            "ln2_g": ("layer", None), "ln2_b": ("layer", None),
            "fc_w": ("layer", "embed", "mlp"),
            "fc_b": ("layer", "mlp"),
            "out_w": ("layer", "mlp", "embed"),
            "out_b": ("layer", None),
        },
        "lnf_g": (None,), "lnf_b": (None,),
    }


def gpt2_rules() -> dict:
    """Logical-axis → mesh-axis rules: TP splits heads and the MLP
    hidden dim; FSDP shards the embedding dim; layer axis is scanned,
    never sharded."""
    return {"vocab": None, "embed": "fsdp", "heads": "tp",
            "mlp": "tp", "layer": None, "batch": ("dp", "fsdp"),
            "seq": "sp"}


def param_shardings(config: Gpt2Config, mesh) -> dict:
    """NamedSharding pytree for jit in_shardings / device_put."""
    from ant_ray_tpu.parallel.sharding import named_sharding  # noqa: PLC0415

    rules = gpt2_rules()

    def _walk(node):
        if isinstance(node, dict):
            return {k: _walk(v) for k, v in node.items()}
        return named_sharding(mesh, node, rules)

    return _walk(param_logical_dims(config))


def init_params(config: Gpt2Config, key) -> dict:
    """GPT-2 init: N(0, 0.02) weights, zero biases, unit LN gains."""
    shapes = param_shapes(config)
    names, leaves = [], []

    def _collect(node, prefix):
        for k, v in node.items():
            if isinstance(v, dict):
                _collect(v, prefix + (k,))
            else:
                names.append(prefix + (k,))
                leaves.append(v)

    _collect(shapes, ())
    keys = jax.random.split(key, len(leaves))

    def _init(name, shape, k):
        leaf = name[-1]
        if leaf.endswith("_b"):
            return jnp.zeros(shape, config.dtype)
        if leaf.endswith("_g"):
            return jnp.ones(shape, config.dtype)
        return (0.02 * jax.random.normal(k, shape)).astype(config.dtype)

    params: dict = {}
    for name, shape, k in zip(names, leaves, keys):
        node = params
        for part in name[:-1]:
            node = node.setdefault(part, {})
        node[name[-1]] = _init(name, shape, k)
    return params


def _layernorm(x, g, b, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * g + b




def _block(layer: dict, x, config: Gpt2Config):
    B, T, D = x.shape
    H, hd = config.n_heads, config.head_dim
    h = _layernorm(x, layer["ln1_g"], layer["ln1_b"], config.norm_eps)
    qkv = h @ layer["qkv_w"] + layer["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, hd)
    k = k.reshape(B, T, H, hd)
    v = v.reshape(B, T, H, hd)
    att = attention(q, k, v, causal=True).reshape(B, T, D)
    x = x + att @ layer["proj_w"] + layer["proj_b"]
    h = _layernorm(x, layer["ln2_g"], layer["ln2_b"], config.norm_eps)
    # GPT-2 uses the tanh GELU approximation (HF "gelu_new").
    h = jax.nn.gelu(h @ layer["fc_w"] + layer["fc_b"], approximate=True)
    x = x + h @ layer["out_w"] + layer["out_b"]
    return x


def forward(params: dict, tokens, config: Gpt2Config) -> jax.Array:
    """Logits for a [B, T] int32 token batch."""
    B, T = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:T]

    def body(carry, layer):
        return jax.checkpoint(
            lambda c, la: _block(la, c, config))(carry, layer), None

    x, _ = lax.scan(body, x, params["layers"])
    x = _layernorm(x, params["lnf_g"], params["lnf_b"], config.norm_eps)
    return x @ params["wte"].T          # tied LM head


def loss_fn(params: dict, batch: dict, config: Gpt2Config) -> jax.Array:
    """Next-token loss; same batch contract as llama.loss_fn — an
    optional ``mask`` excludes padding positions."""
    tokens = batch["tokens"]
    logits = forward(params, tokens[:, :-1], config)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(
        logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("mask")
    if mask is not None:
        mask = mask[:, 1:]
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)


def from_hf_state_dict(state: dict, config: Gpt2Config) -> dict:
    """Convert a HuggingFace ``GPT2LMHeadModel.state_dict()`` (torch
    tensors or numpy arrays) to this module's param pytree.  HF's
    Conv1D stores weights as (in_features, out_features) — the same
    orientation this model multiplies with, so weights pass through
    unchanged; only the per-layer tensors are stacked on the leading
    layer axis for lax.scan."""
    import numpy as np

    def _np(t):
        return np.asarray(t.detach().cpu().numpy()
                          if hasattr(t, "detach") else t)

    def stack(fmt):
        return jnp.asarray(np.stack(
            [_np(state[fmt.format(i)]) for i in range(config.n_layers)]
        ), config.dtype)

    return {
        "wte": jnp.asarray(_np(state["transformer.wte.weight"]),
                           config.dtype),
        "wpe": jnp.asarray(_np(state["transformer.wpe.weight"]),
                           config.dtype),
        "layers": {
            "ln1_g": stack("transformer.h.{}.ln_1.weight"),
            "ln1_b": stack("transformer.h.{}.ln_1.bias"),
            "qkv_w": stack("transformer.h.{}.attn.c_attn.weight"),
            "qkv_b": stack("transformer.h.{}.attn.c_attn.bias"),
            "proj_w": stack("transformer.h.{}.attn.c_proj.weight"),
            "proj_b": stack("transformer.h.{}.attn.c_proj.bias"),
            "fc_w": stack("transformer.h.{}.mlp.c_fc.weight"),
            "fc_b": stack("transformer.h.{}.mlp.c_fc.bias"),
            "out_w": stack("transformer.h.{}.mlp.c_proj.weight"),
            "out_b": stack("transformer.h.{}.mlp.c_proj.bias"),
            "ln2_g": stack("transformer.h.{}.ln_2.weight"),
            "ln2_b": stack("transformer.h.{}.ln_2.bias"),
        },
        "lnf_g": jnp.asarray(_np(state["transformer.ln_f.weight"]),
                             config.dtype),
        "lnf_b": jnp.asarray(_np(state["transformer.ln_f.bias"]),
                             config.dtype),
    }


def flops_per_token(config: Gpt2Config, seq_len: int) -> float:
    """6*N matmul FLOPs + attention term (same accounting as
    llama.flops_per_token)."""
    n = config.num_params()
    attn = 12 * config.n_layers * config.dim * seq_len
    return 6.0 * n + attn
