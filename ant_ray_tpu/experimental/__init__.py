"""Experimental subsystems (mirrors python/ray/experimental/)."""
