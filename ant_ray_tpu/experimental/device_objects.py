"""Device-resident objects: HBM tensors referenced by ObjectRef.

Capability mirror of the reference's "GPU objects" (ref: python/ray/
experimental/gpu_object_manager/gpu_object_manager.py:85,
gpu_object_store.py:170, tensor_transport_manager.py:14), re-designed
for TPU: instead of NCCL/NIXL P2P, the payload stays in the producing
worker's HBM and moves on demand over the **host↔HBM DMA path** —
device→host (one DMA) → RPC → host→device (`jax.device_put`, one DMA)
on the consumer.  The object plane only ever carries small metadata;
big tensors never transit plasma unless fetched.

    ref = device_objects.put(hbm_array)        # metadata ObjectRef
    arr = device_objects.get(ref)              # zero-copy if local

Same-process gets return the identical buffer (no copy at all).
Sharded arrays additionally move over the pluggable COLLECTIVE
transport (tensor_transport.py — shard-by-shard over the actors'
collective group, ICI on hardware; selected automatically from the
sharding metadata recorded at put()); the DMA path is the general
fallback exactly like the reference's object-store transport.
"""

from __future__ import annotations

from typing import Any


def _runtime():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    runtime = global_worker.runtime
    if not hasattr(runtime, "_device_objects"):
        raise RuntimeError(
            "device objects need cluster mode (local_mode has no "
            "per-worker device store)")
    return runtime


def put(array: Any, *, transport: str = "auto",
        group_name: str = "default") -> "object":
    """Register a device array in this worker's device-object store;
    returns an ObjectRef whose payload is just metadata.

    The metadata carries a holder token (not the ObjectRef id): when the
    ref is passed as a task arg, the arg resolves to the metadata dict —
    which remains a fetchable handle, exactly like the reference's
    deserialized GPU-object values.

    ``transport`` mirrors the reference's per-object transport choice
    (ref: gpu_object_manager.py put(..., tensor_transport=...)):
    "auto" records collective-transport metadata when the array is
    sharded AND this process is in collective group ``group_name`` —
    consumers in the group then pull shard-by-shard over it (ICI on
    hardware); everyone else falls back to the DMA path.  "dma" skips
    the probe; "collective" requires it to apply."""
    import uuid  # noqa: PLC0415

    runtime = _runtime()
    token = uuid.uuid4().hex
    meta = {
        "__art_device_object__": True,
        "holder": runtime.address,
        "token": token,
        "shape": tuple(getattr(array, "shape", ())),
        "dtype": str(getattr(array, "dtype", "")),
    }
    if transport in ("auto", "collective"):
        from ant_ray_tpu.experimental.tensor_transport import (  # noqa: PLC0415
            shard_layout,
        )

        layout = shard_layout(array)
        recorded = False
        if layout is not None:
            from ant_ray_tpu.util.collective import collective as col  # noqa: PLC0415

            if col.is_group_initialized(group_name):
                meta["layout"] = layout
                meta["collective"] = {
                    "group": group_name,
                    "src_rank": col.get_rank(group_name)}
                recorded = True
        if transport == "collective" and not recorded:
            raise ValueError(
                "transport='collective' needs a sharded array and an "
                f"initialized collective group {group_name!r}")
    elif transport != "dma":
        raise ValueError(f"unknown transport {transport!r}")
    ref = runtime.put(meta)
    runtime._device_objects[token] = array
    # Payload lifetime rides the metadata object's refcount: when the
    # owner frees the metadata (all refs/borrows gone), the HBM entry
    # is dropped too.  A grace pin covers the window between returning
    # the ref from a task and the consumer's borrow registration.
    runtime._device_tokens_by_oid[ref.id] = token
    runtime.pin_for_grace(ref)
    return ref


def get(ref_or_meta, timeout: float | None = None) -> Any:
    """Resolve a device ObjectRef (or its resolved metadata dict, as
    seen inside a task that received the ref as an argument) to an
    array on this process' device.

    Local hit → the original buffer (zero copy).  Remote → holder DMAs
    to host, bytes travel by RPC, and the result is `device_put` here.
    """
    runtime = _runtime()
    meta = _resolve_meta(runtime, ref_or_meta, timeout)
    local = runtime._device_objects.get(meta["token"])
    if local is not None:
        return local
    from ant_ray_tpu.experimental.tensor_transport import (  # noqa: PLC0415
        select_transport,
    )

    return select_transport(meta, runtime).fetch(meta, runtime, timeout)


def free(ref_or_meta) -> None:
    """Drop the device payload (metadata object follows normal ref
    counting)."""
    runtime = _runtime()
    meta = _resolve_meta(runtime, ref_or_meta, 5)
    if runtime._device_objects.pop(meta["token"], None) is not None:
        return
    runtime._send_oneway(meta["holder"], "DeviceTensorFree",
                         {"token": meta["token"]})


def _resolve_meta(runtime, ref_or_meta, timeout) -> dict:
    meta = ref_or_meta
    if not isinstance(meta, dict):
        meta = runtime.get([ref_or_meta], timeout)[0]
    if not (isinstance(meta, dict) and meta.get("__art_device_object__")):
        raise TypeError("not a device ObjectRef / device-object metadata")
    return meta
