"""Device-resident objects: HBM tensors referenced by ObjectRef.

Capability mirror of the reference's "GPU objects" (ref: python/ray/
experimental/gpu_object_manager/gpu_object_manager.py:85,
gpu_object_store.py:170, tensor_transport_manager.py:14), re-designed
for TPU: instead of NCCL/NIXL P2P, the payload stays in the producing
worker's HBM and moves on demand over the **host↔HBM DMA path** —
device→host (one DMA) → RPC → host→device (`jax.device_put`, one DMA)
on the consumer.  The object plane only ever carries small metadata;
big tensors never transit plasma unless fetched.

    ref = device_objects.put(hbm_array)        # metadata ObjectRef
    arr = device_objects.get(ref)              # zero-copy if local

Same-process gets return the identical buffer (no copy at all).  An
in-slice ICI transport (XLA collective send/recv between jitted mesh
programs) is the planned fast path for sharded arrays; the DMA path is
the general fallback exactly like the reference's object-store
transport.
"""

from __future__ import annotations

from typing import Any


def _runtime():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker._check_connected()
    runtime = global_worker.runtime
    if not hasattr(runtime, "_device_objects"):
        raise RuntimeError(
            "device objects need cluster mode (local_mode has no "
            "per-worker device store)")
    return runtime


def put(array: Any) -> "object":
    """Register a device array in this worker's device-object store;
    returns an ObjectRef whose payload is just metadata.

    The metadata carries a holder token (not the ObjectRef id): when the
    ref is passed as a task arg, the arg resolves to the metadata dict —
    which remains a fetchable handle, exactly like the reference's
    deserialized GPU-object values."""
    import uuid  # noqa: PLC0415

    runtime = _runtime()
    token = uuid.uuid4().hex
    meta = {
        "__art_device_object__": True,
        "holder": runtime.address,
        "token": token,
        "shape": tuple(getattr(array, "shape", ())),
        "dtype": str(getattr(array, "dtype", "")),
    }
    ref = runtime.put(meta)
    runtime._device_objects[token] = array
    # Payload lifetime rides the metadata object's refcount: when the
    # owner frees the metadata (all refs/borrows gone), the HBM entry
    # is dropped too.  A grace pin covers the window between returning
    # the ref from a task and the consumer's borrow registration.
    runtime._device_tokens_by_oid[ref.id] = token
    runtime.pin_for_grace(ref)
    return ref


def get(ref_or_meta, timeout: float | None = None) -> Any:
    """Resolve a device ObjectRef (or its resolved metadata dict, as
    seen inside a task that received the ref as an argument) to an
    array on this process' device.

    Local hit → the original buffer (zero copy).  Remote → holder DMAs
    to host, bytes travel by RPC, and the result is `device_put` here.
    """
    runtime = _runtime()
    from ant_ray_tpu import exceptions  # noqa: PLC0415

    meta = _resolve_meta(runtime, ref_or_meta, timeout)
    local = runtime._device_objects.get(meta["token"])
    if local is not None:
        return local
    try:
        host = runtime._fetch_device_tensor(meta["holder"], meta["token"],
                                            timeout)
    except Exception as e:  # noqa: BLE001 — holder died / unreachable
        raise exceptions.ObjectLostError(
            None, f"holder of device object {meta['token'][:12]} is "
            f"unreachable: {e}") from e
    if host is None:
        raise exceptions.ObjectLostError(
            None, f"holder no longer has device object "
            f"{meta['token'][:12]}")
    from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

    jax = import_jax()
    return jax.device_put(host)


def free(ref_or_meta) -> None:
    """Drop the device payload (metadata object follows normal ref
    counting)."""
    runtime = _runtime()
    meta = _resolve_meta(runtime, ref_or_meta, 5)
    if runtime._device_objects.pop(meta["token"], None) is not None:
        return
    runtime._send_oneway(meta["holder"], "DeviceTensorFree",
                         {"token": meta["token"]})


def _resolve_meta(runtime, ref_or_meta, timeout) -> dict:
    meta = ref_or_meta
    if not isinstance(meta, dict):
        meta = runtime.get([ref_or_meta], timeout)[0]
    if not (isinstance(meta, dict) and meta.get("__art_device_object__")):
        raise TypeError("not a device ObjectRef / device-object metadata")
    return meta
