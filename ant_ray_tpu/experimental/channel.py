"""Shared-memory mutable-object channels.

The substrate of compiled step graphs (ref:
src/ray/core_worker/experimental_mutable_object_manager.h:44 — mutable
plasma objects with writer/reader semaphores; python surface
python/ray/experimental/channel/shared_memory_channel.py).  Redesigned
lock-free for the tmpfs-arena model: each channel is its own small mmap
file with a version counter + readers-done counter; synchronization is
acquire/release atomics with GIL-released spin-waits in C++
(native/store_core.cpp Channel type).

Protocol: single writer, ``num_readers`` readers.  Every published
version must be read (acquire) and released by every reader before the
next write can begin — the same backpressure contract as the
reference's mutable objects, which is what makes a pipeline of
channel-connected actors self-throttling.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

from ant_ray_tpu._private.native import load_native
from ant_ray_tpu._private.serialization import (
    SerializedObject,
    deserialize,
    serialize,
)

_TAG_VALUE = 0
_TAG_ERROR = 1


class ChannelClosedError(Exception):
    """The channel was torn down (writer or driver called close())."""


class ChannelTimeoutError(Exception):
    pass


class ShmChannel:
    """One mutable shm buffer: ``write(obj)`` / ``begin_read()`` /
    ``end_read()``.  Values are pickled with out-of-band buffer support
    (zero additional copies for numpy/jax host arrays on write)."""

    def __init__(self, path: str, capacity: int = 0, num_readers: int = 1,
                 create: bool = False):
        native = load_native()
        if native is None:
            raise RuntimeError(
                "art_native is unavailable — shm channels need the C++ "
                "extension (no compiler on this host?)")
        self.path = path
        self._ch = native.Channel(path, capacity=capacity,
                                  num_readers=num_readers, create=create)
        self._last_version = 0
        self._reading = False

    # ------------------------------------------------------------ writer

    def write(self, value: Any, timeout: float | None = None) -> None:
        self._write_tagged(_TAG_VALUE, serialize(value).to_payload(),
                           timeout)

    def write_error(self, err: Exception,
                    timeout: float | None = None) -> None:
        self._write_tagged(_TAG_ERROR, pickle.dumps(err), timeout)

    def _write_tagged(self, tag: int, payload: bytes,
                      timeout: float | None) -> None:
        nbytes = len(payload) + 1
        try:
            view = self._ch.write_begin(
                nbytes, -1.0 if timeout is None else timeout)
        except ValueError as e:
            raise ChannelClosedError(str(e)) from None
        except TimeoutError as e:
            raise ChannelTimeoutError(str(e)) from None
        view[0] = tag
        view[1:nbytes] = payload
        self._ch.write_commit(nbytes)

    # ------------------------------------------------------------ reader

    def begin_read(self, timeout: float | None = None) -> Any:
        """Block until a version newer than the last one read arrives;
        returns the deserialized value (raises the payload's error if the
        producer wrote one).  Call :meth:`end_read` when done with it —
        the writer cannot publish the next version until every reader
        has."""
        tag, value = self.begin_read_tagged(timeout)
        if tag == "error":
            self.end_read()
            raise value
        return value

    def begin_read_tagged(
            self, timeout: float | None = None) -> tuple[str, Any]:
        """Like :meth:`begin_read` but returns ("value", v) or
        ("error", exc) without raising — the exec-loop path, where errors
        are propagated values, not control flow."""
        try:
            out = self._ch.read_acquire(
                self._last_version, -1.0 if timeout is None else timeout)
        except ValueError as e:
            raise ChannelClosedError(str(e)) from None
        if out is None:
            raise ChannelTimeoutError(
                f"no new value within {timeout}s on {self.path}")
        version, view = out
        self._last_version = version
        self._reading = True
        tag = view[0]
        body = bytes(view[1:])
        if tag == _TAG_ERROR:
            return ("error", pickle.loads(body))
        return ("value", deserialize(SerializedObject.from_payload(body)))

    def end_read(self) -> None:
        if self._reading:
            self._reading = False
            self._ch.read_release()

    def remove_reader(self) -> int:
        """Reader-death recovery: a registered reader died without
        releasing, so stop requiring its releases forever — the writer
        side unwedges on the next publish attempt (ref: reader-failure
        handling, experimental_mutable_object_manager.h:44).  Call once
        per dead reader from whoever observed the death (the DAG driver
        sees exec-loop actor deaths).  Returns the remaining reader
        count."""
        try:
            return self._ch.remove_reader()
        except ValueError as e:
            raise ChannelClosedError(str(e)) from None

    # ------------------------------------------------------------ misc

    @property
    def version(self) -> int:
        return self._ch.version

    def close(self, unlink: bool = False) -> None:
        try:
            self._ch.close()
        finally:
            if unlink:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


def channel_dir() -> str:
    base = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"
    d = os.path.join(base, "art_channels")
    os.makedirs(d, exist_ok=True)
    return d
