"""Pluggable tensor transports for device objects.

Capability mirror of the reference's tensor-transport plane (ref:
python/ray/experimental/gpu_object_manager/tensor_transport_manager.py:14
— the ABC each transport implements — and
collective_tensor_transport.py:36 / nixl_tensor_transport.py:41, the
collective-group and one-sided implementations), re-designed for TPU:

* **dma** (default, always works): holder DMAs device→host, bytes ride
  the RPC plane, consumer ``device_put``s — the object-store transport
  equivalent.
* **collective**: a *sharded* ``jax.Array`` moves SHARD BY SHARD over
  a ``ray.util.collective``-style group the two actors both joined —
  no single host buffer ever materializes.  On TPU hardware the xla
  backend's sends ride ICI; in tests the gloo backend carries the same
  per-shard protocol on CPU.  The receiver reassembles the array on
  its own mesh (same grid shape, its own devices) with
  ``jax.make_array_from_single_device_arrays``.

Selection is automatic from the metadata the producer recorded at
``device_objects.put`` time (sharding grid + collective group): a
consumer inside the group uses the collective path, anyone else falls
back to dma — mirroring how the reference picks a transport from the
tensor's recorded transport metadata.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

logger = logging.getLogger(__name__)

# One transfer at a time per (group, peer): p2p channels are ordered —
# interleaving two multi-shard transfers on one pair would cross wires.
_fetch_locks: dict = {}
_fetch_locks_guard = threading.Lock()
# Pairs whose recv watchdog expired: their p2p channel may hold a
# dangling recv, so further collective fetches from them fall back to
# dma instead of deadlocking behind the poisoned channel.
_poisoned_pairs: set = set()
# Watchdog for the recv phase when the caller gave no timeout.  The
# holder acked before any recv starts, so a healthy transfer progresses
# immediately; this only bounds a transfer whose sender died mid-way.
_RECV_DEADLINE_S = 300.0


def _pair_lock(group: str, peer: int) -> threading.Lock:
    with _fetch_locks_guard:
        return _fetch_locks.setdefault((group, peer), threading.Lock())


# Outbound transfers serialize PER DESTINATION (p2p channels are
# ordered pair-wise): a wedged consumer must only stall sends to
# itself, never the whole holder.  A module-global lock here would let
# one dead peer wedge every outbound transfer of the process.
_send_locks: dict = {}
_send_locks_guard = threading.Lock()
# Bound on one outbound transfer (lock acquisition + shard sends): on
# expiry the pair is poisoned and the send abandoned — the consumer's
# own watchdog turns the dead transfer into ObjectLost on its side.
_SEND_DEADLINE_S = 300.0


def _send_lock_for(group: str, peer: int) -> threading.Lock:
    with _send_locks_guard:
        return _send_locks.setdefault((group, peer), threading.Lock())


def clear_group(group: str) -> None:
    """Forget all per-pair transport state for ``group`` — called on
    collective-group teardown so a re-initialized group starts with a
    clean slate (stale poisoned-pair markers would dma-degrade the new
    incarnation forever; stale locks could be held by dead threads)."""
    # Snapshot before filtering: watchdog threads add() concurrently,
    # and iterating the live set would raise mid-teardown.
    _poisoned_pairs.difference_update(
        {p for p in list(_poisoned_pairs) if p[0] == group})
    with _fetch_locks_guard:
        for key in [k for k in _fetch_locks if k[0] == group]:
            del _fetch_locks[key]
    with _send_locks_guard:
        for key in [k for k in _send_locks if k[0] == group]:
            del _send_locks[key]


def shards_in_mesh_order(array: Any) -> list:
    """Addressable shards sorted by their device's flat position in the
    mesh grid — the canonical wire order for shard-by-shard transfers
    (sender and receiver must agree; this IS the agreement)."""
    import numpy as np  # noqa: PLC0415

    devices = list(np.asarray(array.sharding.mesh.devices).flatten())
    pos = {id(d): i for i, d in enumerate(devices)}
    return sorted(array.addressable_shards,
                  key=lambda s: pos.get(id(s.device), 1 << 30))


def send_shards(array: Any, dst_rank: int, group: str,
                deadline_s: float | None = None) -> None:
    """Holder side of the collective transport: push each shard in mesh
    order over the p2p channel (called from the DeviceTensorSendVia
    RPC, off the io loop).  Failures are logged, not raised — the RPC
    already acked; the consumer's recv watchdog turns a dead transfer
    into ObjectLost + pair poisoning on its side.

    Sends serialize per destination (pair-ordered channels) and are
    bounded by ``deadline_s`` (default ``_SEND_DEADLINE_S``): a dead
    consumer poisons only its own pair instead of wedging every
    outbound transfer of this process behind one global lock."""
    import numpy as np  # noqa: PLC0415

    from ant_ray_tpu.util.collective import collective as col  # noqa: PLC0415

    budget = deadline_s if deadline_s is not None else _SEND_DEADLINE_S
    # ONE deadline for lock acquisition + sends — not budget each, so a
    # caller queued behind a stalled transfer still observes the
    # documented bound rather than up to twice it.
    deadline_at = time.monotonic() + budget
    if (group, dst_rank) in _poisoned_pairs:
        logger.warning("skipping shard send to rank %d over %r: pair is "
                       "poisoned (previous transfer stalled)",
                       dst_rank, group)
        return
    lock = _send_lock_for(group, dst_rank)
    if not lock.acquire(timeout=budget):
        _poisoned_pairs.add((group, dst_rank))
        logger.error("send lock for rank %d over %r not acquired within "
                     "%.0fs; pair poisoned", dst_rank, group, budget)
        return
    try:
        abort = threading.Event()

        def _send_all() -> None:
            for shard in shards_in_mesh_order(array):
                if abort.is_set():
                    return     # abandoned: stop at a shard boundary so
                    # a later incarnation of the group never sees our
                    # remaining shards interleaved into its channel
                col.send(np.asarray(shard.data), dst_rank, group)

        import concurrent.futures as cf  # noqa: PLC0415

        pool = cf.ThreadPoolExecutor(max_workers=1)
        fut = pool.submit(_send_all)
        try:
            fut.result(max(0.1, deadline_at - time.monotonic()))
        except cf.TimeoutError:
            abort.set()
            _poisoned_pairs.add((group, dst_rank))
            logger.error("collective shard send to rank %d over %r "
                         "stalled for %.0fs; pair poisoned, send "
                         "abandoned", dst_rank, group, budget)
        finally:
            # wait=False: an expired send thread is parked in an
            # uninterruptible send — joining it would re-wedge us.
            pool.shutdown(wait=False)
    except Exception:  # noqa: BLE001 — surfaced on the consumer side
        logger.exception("collective shard send to rank %d over %r "
                         "failed", dst_rank, group)
    finally:
        lock.release()


def shard_layout(array: Any) -> dict | None:
    """Producer-side transport metadata for a sharded jax.Array: the
    mesh grid, the partition spec, and each shard's (flat mesh
    position, shape) — everything a receiver needs to pre-allocate
    recv buffers and rebuild the sharding on its own devices (the
    reference's extract_tensor_transport_metadata equivalent)."""
    sharding = getattr(array, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is None or spec is None:
        return None
    try:
        import numpy as np  # noqa: PLC0415

        devices = list(np.asarray(mesh.devices).flatten())
        pos = {id(d): i for i, d in enumerate(devices)}
        shards = shards_in_mesh_order(array)
        if len(shards) <= 1 or len(shards) != len(devices):
            return None            # single-shard or multi-host: use dma
        return {
            "mesh_shape": tuple(mesh.devices.shape),
            "axis_names": tuple(mesh.axis_names),
            "spec": tuple(None if p is None else p for p in spec),
            "shards": [{
                "pos": pos[id(s.device)],
                "shape": tuple(s.data.shape),
                "dtype": str(s.data.dtype),
            } for s in shards],
        }
    except Exception:  # noqa: BLE001 — layout probing is best-effort
        return None


class TensorTransport:
    """One way of moving a device tensor holder→consumer (ref:
    tensor_transport_manager.py:14 TensorTransportManager)."""

    name = "base"

    @staticmethod
    def can_fetch(meta: dict, runtime) -> bool:
        raise NotImplementedError

    @staticmethod
    def fetch(meta: dict, runtime, timeout: float | None) -> Any:
        raise NotImplementedError


class DmaTransport(TensorTransport):
    """device→host DMA + RPC + host→device (the always-available
    object-store-style fallback)."""

    name = "dma"

    @staticmethod
    def can_fetch(meta: dict, runtime) -> bool:
        return True

    @staticmethod
    def fetch(meta: dict, runtime, timeout: float | None) -> Any:
        from ant_ray_tpu import exceptions  # noqa: PLC0415

        try:
            host = runtime._fetch_device_tensor(
                meta["holder"], meta["token"], timeout)
        except Exception as e:  # noqa: BLE001 — holder died/unreachable
            raise exceptions.ObjectLostError(
                None, f"holder of device object {meta['token'][:12]} is "
                f"unreachable: {e}") from e
        if host is None:
            raise exceptions.ObjectLostError(
                None, f"holder no longer has device object "
                f"{meta['token'][:12]}")
        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

        return import_jax().device_put(host)


class CollectiveTransport(TensorTransport):
    """Shard-by-shard transfer over the collective group both actors
    joined (ref: collective_tensor_transport.py:36).  The consumer
    triggers the holder (oneway RPC), then receives each shard in mesh
    order and reassembles on a local mesh of the same grid shape."""

    name = "collective"

    @staticmethod
    def can_fetch(meta: dict, runtime) -> bool:
        xfer = meta.get("collective")
        if not xfer or not meta.get("layout"):
            return False
        from ant_ray_tpu.util.collective import collective as col  # noqa: PLC0415

        group = xfer["group"]
        if not col.is_group_initialized(group):
            return False
        if (group, xfer["src_rank"]) in _poisoned_pairs:
            return False               # dangling recv on this channel
        return col.get_rank(group) != xfer["src_rank"]

    @staticmethod
    def fetch(meta: dict, runtime, timeout: float | None) -> Any:
        import numpy as np  # noqa: PLC0415

        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415
        from ant_ray_tpu.util.collective import collective as col  # noqa: PLC0415

        jax = import_jax()
        xfer = meta["collective"]
        layout = meta["layout"]
        group, src = xfer["group"], xfer["src_rank"]
        from ant_ray_tpu import exceptions  # noqa: PLC0415

        my_rank = col.get_rank(group)
        with _pair_lock(group, src):
            # Kick the holder's send loop and wait for its ack BEFORE
            # parking in recv: a freed token or dead holder must raise
            # ObjectLost (like the dma path), not hang a recv that
            # nothing will ever match.
            client = runtime._clients.get(meta["holder"])
            try:
                ok = runtime._io.run_coro(client.call_async(
                    "DeviceTensorSendVia",
                    {"token": meta["token"], "group": group,
                     "dst_rank": my_rank}, timeout=30))
            except Exception as e:  # noqa: BLE001 — holder unreachable
                raise exceptions.ObjectLostError(
                    None, f"holder of device object {meta['token'][:12]} "
                    f"is unreachable: {e}") from e
            if not ok:
                raise exceptions.ObjectLostError(
                    None, f"holder no longer has device object "
                    f"{meta['token'][:12]}")

            def _recv_all() -> list:
                out = []
                for shard in layout["shards"]:
                    buf = np.zeros(shard["shape"],
                                   dtype=_np_dtype(shard["dtype"]))
                    # artlint: disable=blocking-under-lock — the pair
                    # lock SERIALIZES this send/recv rendezvous by
                    # design (PR 2 satellite): it is per-(group, src),
                    # and the watchdog below bounds the park.
                    out.append(col.recv(buf, src, group))
                return out

            # Watchdog: recv has no native timeout; a sender that died
            # mid-transfer would otherwise hang this consumer (and the
            # pair lock) forever.  On expiry the pair is poisoned —
            # later fetches from it use dma.
            import concurrent.futures as cf  # noqa: PLC0415

            deadline = timeout if timeout is not None else _RECV_DEADLINE_S
            pool = cf.ThreadPoolExecutor(max_workers=1)
            fut = pool.submit(_recv_all)
            try:
                # artlint: disable=blocking-under-lock — bounded wait
                # by the recv watchdog deadline; the pair lock must
                # stay held until the collective pair is quiesced.
                host_shards = fut.result(deadline)
            except cf.TimeoutError:
                _poisoned_pairs.add((group, src))
                raise exceptions.ObjectLostError(
                    None, f"collective transfer of "
                    f"{meta['token'][:12]} from rank {src} over "
                    f"{group!r} stalled for {deadline:.0f}s; pair "
                    "poisoned, future fetches fall back to dma"
                ) from None
            finally:
                # wait=False: on expiry the recv thread is parked in an
                # uninterruptible recv — joining it would re-hang us.
                pool.shutdown(wait=False)
        # Reassemble on THIS process's devices: same grid, local mesh.
        mesh_shape = tuple(layout["mesh_shape"])
        n = int(np.prod(mesh_shape))
        devices = jax.local_devices()[:n]
        if len(devices) < n:
            # Consumer has fewer devices than the grid: concatenate on
            # host instead (still shard-wise transfer, degraded
            # placement).
            return jax.device_put(
                _host_assemble(np, layout, host_shards, meta))
        mesh = jax.sharding.Mesh(
            np.asarray(devices).reshape(mesh_shape),
            layout["axis_names"])
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(*layout["spec"]))
        flat = list(np.asarray(mesh.devices).flatten())
        arrays = [jax.device_put(np.asarray(data), flat[s["pos"]])
                  for s, data in zip(layout["shards"], host_shards)]
        return jax.make_array_from_single_device_arrays(
            tuple(meta["shape"]), sharding, arrays)


def _np_dtype(name: str):
    import numpy as np  # noqa: PLC0415

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: PLC0415

        return np.dtype(getattr(ml_dtypes, name))


def _host_assemble(np, layout: dict, host_shards: list, meta: dict):
    """Degraded path: rebuild the full array host-side from shards
    (consumer lacks the device grid).  Uses the addressable-shard
    slices implied by an even partition spec."""
    out = np.zeros(tuple(meta["shape"]), dtype=host_shards[0].dtype)
    # Recover each shard's slice from its position in the mesh grid.
    mesh_shape = tuple(layout["mesh_shape"])
    axis_names = layout["axis_names"]
    spec = layout["spec"]
    for s, data in zip(layout["shards"], host_shards):
        coords = np.unravel_index(s["pos"], mesh_shape)
        index = []
        for dim, p in enumerate(spec):
            dim_len = out.shape[dim]
            if p is None:
                index.append(slice(None))
                continue
            names = (p,) if isinstance(p, str) else tuple(p)
            stride = dim_len
            start = 0
            for name in names:
                k = mesh_shape[axis_names.index(name)]
                stride //= k
                start += coords[axis_names.index(name)] * stride
            index.append(slice(start, start + data.shape[dim]))
        out[tuple(index)] = data
    return out


# Ordered by preference: first transport whose can_fetch passes wins.
TRANSPORTS: list[type[TensorTransport]] = [CollectiveTransport,
                                           DmaTransport]


def register_transport(transport: type[TensorTransport],
                       prepend: bool = True) -> None:
    """Plug in a custom transport (the reference's registry,
    tensor_transport_manager.py — e.g. a DCN bulk mover)."""
    if prepend:
        TRANSPORTS.insert(0, transport)
    else:
        TRANSPORTS.append(transport)


def select_transport(meta: dict, runtime) -> type[TensorTransport]:
    for transport in TRANSPORTS:
        try:
            if transport.can_fetch(meta, runtime):
                return transport
        except Exception:  # noqa: BLE001 — a broken plugin must not
            logger.exception("transport %s probe failed", transport.name)
    return DmaTransport
