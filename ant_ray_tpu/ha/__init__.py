"""Head-node high availability (ant-fork capability, ref:
python/ray/ha/ — leader election with lease fencing)."""

from ant_ray_tpu.ha.leader_selector import (
    FileBasedLeaderSelector,
    HeadNodeLeaderSelector,
)

__all__ = ["FileBasedLeaderSelector", "HeadNodeLeaderSelector"]
