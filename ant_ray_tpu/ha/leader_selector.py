"""Leader election for head-node HA.

Capability mirror of the ant fork's Redis-lease leader election
(ref: python/ray/ha/leader_selector.py:8 HeadNodeLeaderSelector ABC,
redis_leader_selector.py:90 RedisBasedLeaderSelector): standby heads
poll a lease; the holder renews it; a holder that misses renewals is
fenced out by expiry and a standby takes over.

Two backends share one lifecycle (:class:`_LeaseSelectorBase` —
acquire / renew / fence / release):

* :class:`FileBasedLeaderSelector` — shared-filesystem lease (atomic
  O_EXCL create + mtime-based expiry), for single-host HA tests and
  NFS deployments without any external service;
* :class:`StoreBasedLeaderSelector` — compare-and-swap TTL lease on
  the RPC'd store service (store_server.py), the cross-MACHINE
  backend: the lease lives on a third party both heads reach, exactly
  the Redis variant's role.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid

logger = logging.getLogger(__name__)


class HeadNodeLeaderSelector:
    """ABC (ref: ha/leader_selector.py:8)."""

    role = "standby"

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def is_leader(self) -> bool:
        return self.role == "leader"

    def wait_until_leader(self, timeout: float | None = None) -> bool:
        raise NotImplementedError


class _LeaseSelectorBase(HeadNodeLeaderSelector):
    """Shared lease lifecycle: a poll thread tries to acquire while
    standby and renews while leader; a failed renew means the lease was
    usurped or the backend is unreachable — either way the holder can
    no longer prove leadership and steps down (fencing).  Backends
    implement ``_try_acquire`` / ``_renew`` / ``_release``."""

    def __init__(self, *, holder_id: str | None = None,
                 lease_ttl_s: float = 3.0, renew_period_s: float = 1.0):
        self._holder = holder_id or f"head-{os.getpid()}"
        self._token = uuid.uuid4().hex
        self._ttl = lease_ttl_s
        self._renew_period = renew_period_s
        self._stop = threading.Event()
        self._became_leader = threading.Event()
        self._thread: threading.Thread | None = None
        # Fencing clock: the holder may act as leader only while
        # monotonic() < lease_valid_until (set at every successful
        # acquire/renew).  Consumers check it BEFORE applying a
        # mutation, so an expired-but-not-yet-demoted holder rejects
        # late writes instead of split-braining (the "old leader's late
        # mutation is rejected" guarantee).
        self.lease_valid_until: float = 0.0
        # Optional role-transition callbacks, invoked from the poll
        # thread (consumers post to their own loop): the replicated GCS
        # hangs its promote/demote sequences here.
        self.on_promote = None
        self.on_demote = None

    # Backend hooks -----------------------------------------------------

    def _try_acquire(self) -> bool:
        raise NotImplementedError

    def _renew(self) -> bool:
        raise NotImplementedError

    def _release(self) -> None:
        raise NotImplementedError

    # Lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _guarded(self, op) -> bool:
        """A raising backend (shared-FS blip raising raw OSError from
        the file lease) must read as 'could not prove the lease', not
        kill the poll thread — a dead selector is a silent zombie that
        can neither lead nor fail over."""
        try:
            return op()
        except Exception:  # noqa: BLE001 — backend blip: stand by
            logger.exception("lease backend error (treated as failure)")
            return False

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.role == "leader":
                # Stamp the validity window BEFORE the backend round
                # trip: the lease is good for ttl from (at latest) the
                # moment the renew was issued, so the window is
                # conservative even when the renew itself is slow.
                stamp = time.monotonic() + self._ttl
                if self._guarded(self._renew):
                    self.lease_valid_until = stamp
                else:
                    # Fenced (or the backend is gone): a leader that
                    # cannot prove its lease must not act.
                    self.lease_valid_until = 0.0
                    self.role = "standby"
                    self._became_leader.clear()
                    callback = self.on_demote
                    if callback is not None:
                        callback()
            else:
                stamp = time.monotonic() + self._ttl
                if self._guarded(self._try_acquire):
                    self.lease_valid_until = stamp
                    self.role = "leader"
                    self._became_leader.set()
                    callback = self.on_promote
                    if callback is not None:
                        callback()
            self._stop.wait(self._renew_period)

    def wait_until_leader(self, timeout: float | None = None) -> bool:
        return self._became_leader.wait(timeout)

    def fencing_token(self) -> str:
        return self._token

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Release the lease if we still hold it so standbys fail over
        # immediately instead of waiting out the TTL.
        try:
            self._release()
        except Exception:  # noqa: BLE001 — best effort
            pass
        self.lease_valid_until = 0.0
        self.role = "standby"
        self._became_leader.clear()


class FileBasedLeaderSelector(_LeaseSelectorBase):
    """Lease file on a shared filesystem.

    The lease is a JSON file {holder, token, renewed_at}, held by
    renewing ``renewed_at`` and considered expired ``lease_ttl_s``
    after the last renewal.  Acquisition is serialized through an
    atomic ``mkdir`` mutex (only one contender enters the
    check-expiry-then-write critical section, so there is no
    dual-leader window); a mutex dir older than the TTL is treated as
    the debris of a crashed contender and removed.  ``fencing_token()``
    returns the holder's token so fenced writes can be rejected
    downstream (same role as the Redis key's value in the reference).
    """

    def __init__(self, lease_path: str, *, holder_id: str | None = None,
                 lease_ttl_s: float = 3.0, renew_period_s: float = 1.0):
        super().__init__(holder_id=holder_id, lease_ttl_s=lease_ttl_s,
                         renew_period_s=renew_period_s)
        self._path = lease_path

    # ---- lease file primitives

    def _read_lease(self) -> dict | None:
        try:
            with open(self._path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write_lease(self) -> None:
        tmp = f"{self._path}.tmp.{os.getpid()}.{self._token[:8]}"
        with open(tmp, "w") as f:
            json.dump({"holder": self._holder, "token": self._token,
                       "renewed_at": time.time()}, f)
        os.rename(tmp, self._path)

    def _try_acquire(self) -> bool:
        lease = self._read_lease()
        if lease is not None:
            if lease.get("token") == self._token:
                return True
            # artlint: disable=banned-apis — renewed_at is a CROSS-
            # PROCESS wire field (the lease file is read by every
            # contender); wall clock is the only clock they share.
            if time.time() - lease.get("renewed_at", 0) < self._ttl:
                return False
        # Expired (or absent) — take the acquisition mutex so exactly
        # one contender fences the old holder.
        mutex = f"{self._path}.acquiring"
        try:
            os.mkdir(mutex)
        except FileExistsError:
            try:
                # artlint: disable=banned-apis — compared against a
                # file mtime, which is wall clock by definition.
                if time.time() - os.path.getmtime(mutex) > self._ttl:
                    os.rmdir(mutex)  # crashed contender's debris
            except OSError:
                pass
            return False
        try:
            lease = self._read_lease()  # re-check under the mutex
            # artlint: disable=banned-apis — renewed_at: cross-process
            # lease-file field, wall clock by design (see above).
            if lease is not None and lease.get("token") != self._token \
                    and time.time() - lease.get("renewed_at", 0) < \
                    self._ttl:
                return False
            self._write_lease()
            return True
        finally:
            try:
                os.rmdir(mutex)
            except OSError:
                pass

    def _renew(self) -> bool:
        lease = self._read_lease()
        if lease is None or lease.get("token") != self._token:
            return False     # usurped while we slept: fenced
        self._write_lease()
        return True

    def _release(self) -> None:
        lease = self._read_lease()
        if lease is not None and lease.get("token") == self._token:
            try:
                os.unlink(self._path)
            except FileNotFoundError:
                pass


class StoreBasedLeaderSelector(_LeaseSelectorBase):
    """Lease against the RPC'd store service (store_server.py) — the
    cross-MACHINE election backend (capability mirror of the ant fork's
    RedisBasedLeaderSelector, ha/redis_leader_selector.py:90: the lease
    lives on a third party both heads can reach, so a standby on
    another machine takes over when the primary stops renewing).

    The store's LeaseAcquire/LeaseRenew are compare-and-swap on the
    holder token, so a fenced ex-leader's renewals are rejected and it
    steps down."""

    _LEASE_NAME = "head-leader"

    def __init__(self, store_address: str, *,
                 holder_id: str | None = None,
                 lease_ttl_s: float = 3.0, renew_period_s: float = 1.0):
        from ant_ray_tpu._private.protocol import ClientPool

        super().__init__(holder_id=holder_id, lease_ttl_s=lease_ttl_s,
                         renew_period_s=renew_period_s)
        self._client = ClientPool().get(
            store_address.removeprefix("art-store://"))

    def _try_acquire(self) -> bool:
        try:
            reply = self._client.call(
                "LeaseAcquire",
                {"name": self._LEASE_NAME, "holder": self._holder,
                 "token": self._token, "ttl": self._ttl}, timeout=5)
            return bool(reply.get("acquired"))
        except Exception:  # noqa: BLE001 — store unreachable: stand by
            return False

    def _renew(self) -> bool:
        try:
            reply = self._client.call(
                "LeaseRenew",
                {"name": self._LEASE_NAME, "token": self._token,
                 "ttl": self._ttl}, timeout=5)
            return bool(reply.get("renewed"))
        except Exception:  # noqa: BLE001
            return False

    def _release(self) -> None:
        self._client.call("LeaseRelease",
                          {"name": self._LEASE_NAME,
                           "token": self._token}, timeout=5)
