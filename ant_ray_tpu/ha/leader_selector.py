"""Leader election for head-node HA.

Capability mirror of the ant fork's Redis-lease leader election
(ref: python/ray/ha/leader_selector.py:8 HeadNodeLeaderSelector ABC,
redis_leader_selector.py:90 RedisBasedLeaderSelector): standby heads
poll a lease; the holder renews it; a holder that misses renewals is
fenced out by expiry and a standby takes over.

The default backend is a shared-filesystem lease (atomic O_EXCL create
+ mtime-based expiry + fencing token), which covers single-host HA
tests and NFS deployments without a Redis dependency; the protocol —
acquire / renew / expire / fence — matches the Redis variant, and a
Redis backend can implement the same ABC where redis is available.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid


class HeadNodeLeaderSelector:
    """ABC (ref: ha/leader_selector.py:8)."""

    role = "standby"

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def is_leader(self) -> bool:
        return self.role == "leader"

    def wait_until_leader(self, timeout: float | None = None) -> bool:
        raise NotImplementedError


class FileBasedLeaderSelector(HeadNodeLeaderSelector):
    """Lease file on a shared filesystem.

    The lease is a JSON file {holder, token, renewed_at}, held by
    renewing ``renewed_at`` and considered expired ``lease_ttl_s``
    after the last renewal.  Acquisition is serialized through an
    atomic ``mkdir`` mutex (only one contender enters the
    check-expiry-then-write critical section, so there is no
    dual-leader window); a mutex dir older than the TTL is treated as
    the debris of a crashed contender and removed.  ``fencing_token()``
    returns the holder's token so fenced writes can be rejected
    downstream (same role as the Redis key's value in the reference).
    """

    def __init__(self, lease_path: str, *, holder_id: str | None = None,
                 lease_ttl_s: float = 3.0, renew_period_s: float = 1.0):
        self._path = lease_path
        self._holder = holder_id or f"head-{os.getpid()}"
        self._token = uuid.uuid4().hex
        self._ttl = lease_ttl_s
        self._renew_period = renew_period_s
        self._stop = threading.Event()
        self._became_leader = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lease file primitives

    def _read_lease(self) -> dict | None:
        try:
            with open(self._path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write_lease(self) -> None:
        tmp = f"{self._path}.tmp.{os.getpid()}.{self._token[:8]}"
        with open(tmp, "w") as f:
            json.dump({"holder": self._holder, "token": self._token,
                       "renewed_at": time.time()}, f)
        os.rename(tmp, self._path)

    def _try_acquire(self) -> bool:
        lease = self._read_lease()
        if lease is not None:
            if lease.get("token") == self._token:
                return True
            if time.time() - lease.get("renewed_at", 0) < self._ttl:
                return False
        # Expired (or absent) — take the acquisition mutex so exactly
        # one contender fences the old holder.
        mutex = f"{self._path}.acquiring"
        try:
            os.mkdir(mutex)
        except FileExistsError:
            try:
                if time.time() - os.path.getmtime(mutex) > self._ttl:
                    os.rmdir(mutex)  # crashed contender's debris
            except OSError:
                pass
            return False
        try:
            lease = self._read_lease()  # re-check under the mutex
            if lease is not None and lease.get("token") != self._token \
                    and time.time() - lease.get("renewed_at", 0) < \
                    self._ttl:
                return False
            self._write_lease()
            return True
        finally:
            try:
                os.rmdir(mutex)
            except OSError:
                pass

    # ---- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire():
                if self.role != "leader":
                    self.role = "leader"
                    self._became_leader.set()
                self._stop.wait(self._renew_period)
                if not self._stop.is_set():
                    lease = self._read_lease()
                    if lease is None or lease.get("token") != self._token:
                        # we were fenced — step down
                        self.role = "standby"
                        self._became_leader.clear()
                    else:
                        self._write_lease()  # renew
            else:
                self.role = "standby"
                self._stop.wait(self._renew_period)

    def wait_until_leader(self, timeout: float | None = None) -> bool:
        return self._became_leader.wait(timeout)

    def fencing_token(self) -> str:
        return self._token

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Release the lease if we still hold it so standbys fail over
        # immediately instead of waiting out the TTL.
        lease = self._read_lease()
        if lease is not None and lease.get("token") == self._token:
            try:
                os.unlink(self._path)
            except FileNotFoundError:
                pass
        self.role = "standby"
