"""Sampling parameters (capability mirror of vLLM's SamplingParams as
used through ref: llm/_internal/serve/configs/)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0        # 0 → greedy
    top_k: int = 0                  # 0 → disabled
    top_p: float = 1.0              # 1 → disabled
    stop_token_ids: tuple = field(default_factory=tuple)
    seed: int | None = None

    def __post_init__(self):
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
