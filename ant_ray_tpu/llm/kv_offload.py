"""Tiered KV-session offload stores for the LLM engine.

When the engine evicts an idle session (`kv_idle_evict_s` LRU sweep or
KV-full admission pressure), it device-gets the session's per-slot KV
slab as host numpy and hands it to one of these stores; on the
session's next token the slab is fetched back (on a background thread —
the engine step loop never blocks on a restore) and re-installed into a
free slot.  The round trip is bitwise exact, so restored sessions'
token streams are identical to uninterrupted runs.

Two tiers:

* :class:`LocalKvStore` — in-process host memory (optionally spilling
  each slab to a file under ``spill_dir``).  No cluster required; this
  is the standalone-engine / unit-test tier, and already moves the
  capacity bound from HBM to host RAM (or disk with ``spill_dir``).
* :class:`ObjectPlaneKvStore` — seals slabs into the object store via
  plain ``art.put`` (reusing the arena → spill tiers, same-node mmap
  pool, and seal/pin machinery of ``object_store.py`` as-is), making
  resident-session count a DISK-bounded number.  With ``vault`` set to
  an actor handle, slabs live on the vault's node instead and restores
  travel the PR 5 bulk channel — which is also what lets chaos tests
  kill the holder mid-restore.
"""

from __future__ import annotations

import itertools
import os
import pickle
import threading
from typing import Any


class KvStoreError(RuntimeError):
    """Typed wrapper: a slab put/get against the backing tier failed."""


class LocalKvStore:
    """Host-memory (optionally file-spilled) slab store.

    ``capacity_slabs`` bounds the in-memory tier; beyond it the least
    recently PUT slab spills to ``spill_dir`` (created lazily).  With
    ``spill_dir=None`` everything stays in the dict — fine for tests.
    """

    def __init__(self, spill_dir: str | None = None,
                 capacity_slabs: int | None = None):
        self._mem: dict[str, Any] = {}       # in-memory slabs only
        self._paths: dict[str, str] = {}     # key -> spill file
        self._order: list[str] = []          # LRU by put time
        self._spill_dir = spill_dir
        self._capacity = capacity_slabs
        # Spill files are named by a monotonic counter, never by
        # hash(key): colliding hashes would silently hand one session
        # another session's bytes.
        self._spill_seq = itertools.count()
        self._lock = threading.Lock()
        self.puts = 0
        self.gets = 0
        self.spills = 0

    def put(self, key: str, slab) -> str:
        with self._lock:
            self.puts += 1
            self._mem[key] = slab
            stale = self._paths.pop(key, None)  # superseded spill file
            if key in self._order:
                self._order.remove(key)
            self._order.append(key)
            # _mem holds only real slabs (spill paths live in _paths),
            # so the capacity check counts exactly capacity_slabs.
            if (self._capacity is not None and self._spill_dir
                    and len(self._mem) > self._capacity):
                victim = self._order.pop(0)
                self._spill(victim, self._mem.pop(victim))
        if stale:
            try:
                os.unlink(stale)
            except OSError:
                pass
        return key

    def _spill(self, key: str, slab):
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir,
                            f"kv-{next(self._spill_seq)}.bin")
        with open(path, "wb") as f:
            pickle.dump(slab, f, protocol=pickle.HIGHEST_PROTOCOL)
        self._paths[key] = path
        self.spills += 1

    def get(self, handle: str):
        with self._lock:
            self.gets += 1
            if handle in self._mem:
                return self._mem[handle]
            path = self._paths.get(handle)
        if path is None:
            raise KvStoreError(f"no slab for session {handle!r}")
        with open(path, "rb") as f:
            return pickle.load(f)

    def delete(self, handle: str):
        with self._lock:
            self._mem.pop(handle, None)
            path = self._paths.pop(handle, None)
            if handle in self._order:
                self._order.remove(handle)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass


class ObjectPlaneKvStore:
    """Slabs live in the distributed object store.

    put → ``art.put`` (local arena create/seal; the store's own
    arena → spill tiering makes cold slabs disk-resident for free);
    get → ``art.get``.  Dropping the ref on delete lets refcount GC
    reclaim the bytes.

    ``vault``: an actor handle with ``put(key, slab)`` / ``fetch(key)``
    / ``drop(key)`` methods (see :class:`KvVault`).  Slabs then resolve
    on the vault's node and every restore is a cross-node bulk-channel
    pull — the deployment shape for engines whose own node has no disk
    headroom, and the seam chaos tests use to kill a holder
    mid-restore.

    ``get_timeout_s`` bounds a restore so a dead holder fails the ONE
    session typed instead of wedging its restore thread forever.
    """

    def __init__(self, vault=None, get_timeout_s: float = 30.0):
        import ant_ray_tpu as art  # noqa: PLC0415

        self._art = art
        self._vault = vault
        self._timeout = get_timeout_s
        self._refs: dict[str, Any] = {}
        self._lock = threading.Lock()
        self.puts = 0
        self.gets = 0

    def put(self, key: str, slab) -> str:
        self.puts += 1
        if self._vault is not None:
            self._art.get(self._vault.put.remote(key, slab),
                          timeout=self._timeout)
        else:
            ref = self._art.put(slab)
            with self._lock:
                self._refs[key] = ref
        return key

    def get(self, handle: str):
        self.gets += 1
        if self._vault is not None:
            return self._art.get(self._vault.fetch.remote(handle),
                                 timeout=self._timeout)
        with self._lock:
            ref = self._refs.get(handle)
        if ref is None:
            raise KvStoreError(f"no slab ref for session {handle!r}")
        return self._art.get(ref, timeout=self._timeout)

    def delete(self, handle: str):
        if self._vault is not None:
            try:
                self._vault.drop.remote(handle)
            except Exception:
                pass
            return
        with self._lock:
            self._refs.pop(handle, None)


class KvVault:
    """Remote slab holder: place with ``art.remote(KvVault).options(...)``
    on the node that should own evicted sessions' bytes.  Fetches return
    the slab through the normal large-return path (object store +
    chunked bulk pull), so `testing_chunk_serve_delay_s` and holder
    chaos apply to restores exactly as to any other object read."""

    def __init__(self):
        self._slabs: dict[str, Any] = {}

    def put(self, key: str, slab):
        self._slabs[key] = slab
        return True

    def fetch(self, key: str):
        if key not in self._slabs:
            raise KvStoreError(f"vault has no slab {key!r}")
        return self._slabs[key]

    def drop(self, key: str):
        self._slabs.pop(key, None)
        return True
