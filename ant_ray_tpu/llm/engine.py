"""Continuous-batching LLM engine on the framework's own JAX models.

Capability mirror of the reference's vLLM engine integration (ref:
llm/_internal/serve/engines/vllm/vllm_engine.py, batch/stages/
vllm_engine_stage.py) designed for TPU/XLA rather than around CUDA:

* **Static shapes everywhere.** The decode step is one jitted function
  over a fixed number of slots; prefill lengths are bucketed to powers
  of two, so the engine compiles O(log max_seq) prefill variants and
  exactly one decode variant.
* **Dense per-slot KV slabs** (models/llama.py `init_kv_cache`) instead
  of paged KV: XLA cannot tile dynamic gather-heavy paging the way a
  CUDA kernel can, while dense slabs keep decode attention a plain
  masked matmul on the MXU.  Slot reuse gives the same
  admit-new-work-each-step behavior as paged attention's block reuse.
* **Continuous batching**: each `step()` admits at most one queued
  prompt (prefill) and then decodes every active slot in one batched
  call — the scheduling loop from vLLM reduced to its TPU-friendly
  core.
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ant_ray_tpu.llm.sampling import SamplingParams
from ant_ray_tpu.llm.tokenizer import get_tokenizer


@dataclass
class RequestOutput:
    request_id: str
    prompt_token_ids: list
    token_ids: list = field(default_factory=list)
    text: str = ""
    finished: bool = False
    finish_reason: str | None = None


@dataclass
class _Seq:
    request_id: str
    prompt: list
    sampling: SamplingParams
    slot: int = -1
    generated: list = field(default_factory=list)
    rng_key: Any = None


def _bucket(n: int, cap: int) -> int:
    b = 16
    while b < n:
        b *= 2
    return min(b, cap)


class LLMEngine:
    """Synchronous engine core; Serve replicas and batch stages drive it.

    ``model`` is a config name from models/llama.CONFIGS, a LOCAL
    CHECKPOINT DIRECTORY (HF Llama layout — real weights, loaded via
    models/checkpoint.py), or a LlamaConfig; ``params`` overrides both
    (random init remains the default for named configs: tests/bench).
    """

    def __init__(self, model="tiny", params=None, *, slots: int = 8,
                 max_seq: int | None = None, tokenizer=None,
                 seed: int = 0, tensor_parallel_size: int = 1,
                 mesh=None, max_waiting: int | None = None):
        """``tensor_parallel_size > 1`` makes the ENGINE build a tp mesh
        over this process's local devices and shard params + KV slabs
        itself (ref: vllm_models.py:222 tensor_parallel_size — serving
        an 8B on a slice needs no caller-side sharding).  ``mesh``
        overrides it with a prebuilt mesh (e.g. tp×sp for long-prompt
        prefill via ring attention — forward() switches on sp>1)."""
        from ant_ray_tpu._private.jax_utils import import_jax

        self._jax = jax = import_jax()
        import jax.numpy as jnp  # noqa: PLC0415

        self._jnp = jnp
        from ant_ray_tpu.models import llama  # noqa: PLC0415

        self._llama = llama
        loaded = None
        if isinstance(model, str):
            from ant_ray_tpu.models import checkpoint as ckpt  # noqa: PLC0415
            from ant_ray_tpu.models.llama import CONFIGS  # noqa: PLC0415

            if params is not None and model not in CONFIGS:
                # Explicit (e.g. pre-sharded) params: only the config is
                # needed — don't read gigabytes of weights to drop them.
                if not os.path.isdir(model):
                    raise ValueError(
                        f"model {model!r} is neither a named config "
                        f"{sorted(CONFIGS)} nor a local checkpoint "
                        "directory")
                self.config = ckpt.config_from_hf(model)
            else:
                loaded, self.config = ckpt.resolve_model(model)
            if tokenizer is None and model not in CONFIGS:
                tokenizer = get_tokenizer(model)  # checkpoint dir
        else:
            self.config = model
        self.max_seq = min(max_seq or self.config.max_seq,
                           self.config.max_seq)
        self.slots = slots
        self.tokenizer = tokenizer or get_tokenizer(None)
        if params is None:
            params = (loaded if loaded is not None
                      else llama.init_params(self.config,
                                             jax.random.PRNGKey(seed)))
        self.mesh = mesh
        if tensor_parallel_size > 1 and mesh is None:
            from ant_ray_tpu.parallel.mesh import build_mesh  # noqa: PLC0415

            self.mesh = build_mesh(
                devices=jax.local_devices()[:tensor_parallel_size],
                tp=tensor_parallel_size)
        self.params = params
        self.cache = llama.init_kv_cache(self.config, slots, self.max_seq)
        if self.mesh is not None:
            self._shard_state()
        # Host-side mirror of each slot's most recent token: mutated in
        # numpy and uploaded once per decode call, so the scheduling
        # loop costs one host→device transfer per step instead of one
        # tiny device op per slot.
        self._last_np = np.zeros((slots,), np.int32)
        # Admission bound: with every KV slot busy, at most this many
        # requests may wait for one (None = unbounded, legacy).  Serving
        # paths set it so a traffic spike sheds typed BackPressureError
        # at admission instead of queueing prompts toward OOM.
        self._max_waiting = max_waiting
        self._free_slots = list(range(slots))
        self._active: dict[int, _Seq] = {}        # slot -> seq
        self._waiting: list[_Seq] = []
        self._finished: list[RequestOutput] = []
        self._req_counter = itertools.count()
        self._base_key = jax.random.PRNGKey(seed ^ 0x5EED)

        cfg = self.config
        eng_mesh = self.mesh

        def _prefill(params, cache, tokens, slot, length):
            return llama.prefill_into_cache(params, tokens, cache, slot,
                                            length, cfg, mesh=eng_mesh)

        def _decode(params, cache, last_tokens):
            return llama.decode_step(params, last_tokens, cache, cfg)

        # one compile per prompt bucket (slot/length traced); one decode
        self._prefill_jit = jax.jit(_prefill, donate_argnums=(1,))
        self._decode_jit = jax.jit(_decode, donate_argnums=(1,))
        self._sample_jit = jax.jit(self._sample_batch)

    def _shard_state(self):
        """Distribute params and KV slabs over the engine's mesh: params
        by the model's logical-axis rules (heads/mlp over tp), slabs by
        kv-head over tp — decode attention then runs fully sharded with
        XLA inserting the one all-reduce per block (ref capability:
        vLLM tensor_parallel_size, engine-owned sharding)."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: PLC0415

        mesh = self.mesh
        tp = mesh.shape.get("tp", 1)
        if self.config.n_kv_heads % tp or self.config.n_heads % tp:
            raise ValueError(
                f"tensor_parallel_size={tp} must divide n_heads="
                f"{self.config.n_heads} and n_kv_heads="
                f"{self.config.n_kv_heads}")
        shardings = self._llama.param_shardings(self.config, mesh)
        self.params = jax.device_put(self.params, shardings)
        kv = NamedSharding(mesh, P(None, None, None, "tp", None))
        rep = NamedSharding(mesh, P())
        self.cache = {
            "k": jax.device_put(self.cache["k"], kv),
            "v": jax.device_put(self.cache["v"], kv),
            "length": jax.device_put(self.cache["length"], rep),
        }

    # ------------------------------------------------------------ public

    def add_request(self, prompt, sampling: SamplingParams | None = None,
                    request_id: str | None = None, *,
                    admit: bool = True) -> str:
        """prompt: str (tokenized here) or token-id list.

        With ``max_waiting`` configured and ``admit=True`` (the serving
        default), a request arriving while every KV slot is busy and the
        waiting line is full is REJECTED with
        :class:`~ant_ray_tpu.exceptions.BackPressureError` — admission
        control at the engine boundary, so overload sheds instead of
        growing an unbounded prompt queue toward OOM.  Offline batch
        paths (``generate``) pass ``admit=False``: a caller handing the
        engine a fixed batch wants queueing."""
        if (admit and self._max_waiting is not None
                and not self._free_slots
                and len(self._waiting) >= self._max_waiting):
            from ant_ray_tpu.exceptions import BackPressureError  # noqa: PLC0415

            raise BackPressureError(
                f"engine at capacity: {self.slots} KV slots busy, "
                f"{len(self._waiting)} waiting (max_waiting="
                f"{self._max_waiting})", retry_after_s=0.5)
        sampling = sampling or SamplingParams()
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
        else:
            token_ids = list(prompt)
        if not token_ids:
            raise ValueError("empty prompt")
        budget = max(1, self.max_seq - sampling.max_tokens)
        if len(token_ids) > budget:
            token_ids = token_ids[-budget:]      # keep the suffix
        rid = request_id or f"req-{next(self._req_counter)}"
        seq = _Seq(rid, token_ids, sampling)
        seed = sampling.seed
        key = (self._jax.random.PRNGKey(seed) if seed is not None
               else self._jax.random.fold_in(self._base_key, hash(rid)
                                             & 0x7FFFFFFF))
        seq.rng_key = key
        self._waiting.append(seq)
        return rid

    def has_unfinished(self) -> bool:
        return bool(self._waiting or self._active)

    def step(self) -> list[RequestOutput]:
        """One engine iteration: admit one prompt, decode all active
        slots, release finished ones.  Returns outputs finished since
        the last call."""
        jnp = self._jnp
        if self._waiting and self._free_slots:
            seq = self._waiting.pop(0)
            slot = self._free_slots.pop()
            seq.slot = slot
            bucket = _bucket(len(seq.prompt), self.max_seq)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :len(seq.prompt)] = seq.prompt
            last_logits, self.cache = self._prefill_jit(
                self.params, self.cache, jnp.asarray(padded), slot,
                len(seq.prompt))
            tok = int(self._sample_one(seq, last_logits))
            self._after_token(seq, tok)
            if seq.slot >= 0:
                self._last_np[slot] = tok
                self._active[slot] = seq

        if self._active:
            logits, self.cache = self._decode_jit(
                self.params, self.cache, jnp.asarray(self._last_np))
            toks = np.asarray(self._sample_all(logits))
            for slot, seq in list(self._active.items()):
                tok = int(toks[slot])
                self._after_token(seq, tok)
                if seq.slot >= 0:
                    self._last_np[slot] = tok

        done, self._finished = self._finished, []
        return done

    def generate(self, prompts, sampling: SamplingParams | None = None,
                 ) -> list[RequestOutput]:
        """Run a batch of prompts to completion (offline inference)."""
        order = [self.add_request(p, sampling, admit=False)
                 for p in prompts]
        outputs: dict[str, RequestOutput] = {}
        while self.has_unfinished():
            for out in self.step():
                outputs[out.request_id] = out
        return [outputs[rid] for rid in order]

    def stream(self, prompt, sampling: SamplingParams | None = None):
        """Incremental generation for one request: yields a dict per new
        token ({"token_id", "text", "finished": False}) and a final
        summary chunk ({"finished": True, "finish_reason", "token_ids",
        "full_text"}) — the serving-side source for SSE token streaming
        (ref capability: vllm engine streaming outputs)."""
        rid = self.add_request(prompt, sampling)
        seq = self._waiting[-1]
        assert seq.request_id == rid
        emitted = 0
        final: RequestOutput | None = None
        while final is None and self.has_unfinished():
            for out in self.step():
                if out.request_id == rid:
                    final = out
            source = final.token_ids if final else seq.generated
            while emitted < len(source):
                tok = int(source[emitted])
                emitted += 1
                yield {"token_id": tok,
                       "text": self.tokenizer.decode([tok]),
                       "finished": False,
                       "finish_reason": None}
        yield {"token_id": None,
               "text": "",
               "finished": True,
               "finish_reason": (final.finish_reason if final
                                 else "length"),
               "token_ids": list(final.token_ids) if final else [],
               "full_text": final.text if final else ""}

    # ----------------------------------------------------------- private

    def _after_token(self, seq: _Seq, tok: int):
        seq.generated.append(tok)
        s = seq.sampling
        eos = getattr(self.tokenizer, "eos_id",
                      getattr(self.tokenizer, "eos_token_id", None))
        stop = set(s.stop_token_ids)
        if eos is not None:
            stop.add(int(eos))
        reason = None
        if tok in stop:
            reason = "stop"
        elif len(seq.generated) >= s.max_tokens:
            reason = "length"
        elif len(seq.prompt) + len(seq.generated) >= self.max_seq:
            reason = "length"
        if reason is not None:
            self._release(seq, reason)

    def _release(self, seq: _Seq, reason: str):
        out_ids = (seq.generated[:-1] if reason == "stop"
                   else seq.generated)
        self._finished.append(RequestOutput(
            request_id=seq.request_id,
            prompt_token_ids=seq.prompt,
            token_ids=list(out_ids),
            text=self.tokenizer.decode(out_ids),
            finished=True,
            finish_reason=reason,
        ))
        if seq.slot >= 0:
            self._active.pop(seq.slot, None)
            self._free_slots.append(seq.slot)
            seq.slot = -1

    def _sample_one(self, seq: _Seq, logits):
        seq.rng_key, sub = self._jax.random.split(seq.rng_key)
        s = seq.sampling
        return self._sample_jit(
            logits[None], sub[None],
            self._jnp.asarray([s.temperature], self._jnp.float32),
            self._jnp.asarray([s.top_k], self._jnp.int32),
            self._jnp.asarray([s.top_p], self._jnp.float32))[0]

    def _sample_all(self, logits):
        jnp = self._jnp
        temps = np.zeros((self.slots,), np.float32)
        top_ks = np.zeros((self.slots,), np.int32)
        top_ps = np.ones((self.slots,), np.float32)
        keys = np.zeros((self.slots, 2), np.uint32)
        for slot, seq in self._active.items():
            s = seq.sampling
            temps[slot] = s.temperature
            top_ks[slot] = s.top_k
            top_ps[slot] = s.top_p
            seq.rng_key, sub = self._jax.random.split(seq.rng_key)
            keys[slot] = np.asarray(sub)
        return self._sample_jit(
            logits, jnp.asarray(keys), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps))

    def _sample_batch(self, logits, keys, temps, top_ks, top_ps):
        """Vectorized per-slot sampling: greedy when temperature == 0,
        else temperature softmax with optional top-k / top-p (nucleus)
        filtering — all branch-free for XLA."""
        jax, jnp = self._jax, self._jnp
        vocab = logits.shape[-1]
        greedy = jnp.argmax(logits, axis=-1)

        scaled = logits / jnp.maximum(temps[:, None], 1e-6)
        # top-k: mask everything below the k-th largest (k==0 → keep all)
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        k_idx = jnp.clip(top_ks - 1, 0, vocab - 1)
        kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
        keep_k = (top_ks[:, None] <= 0) | (scaled >= kth)
        # top-p: smallest prefix of the sorted distribution with
        # cumulative prob >= p
        probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs_sorted, axis=-1)
        cutoff_rank = jnp.sum(cum < top_ps[:, None], axis=-1)  # inclusive
        ranks = jnp.argsort(jnp.argsort(-scaled, axis=-1), axis=-1)
        keep_p = ranks <= cutoff_rank[:, None]
        masked = jnp.where(keep_k & keep_p, scaled, -jnp.inf)
        sampled = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg))(keys, masked)
        return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
