"""Continuous-batching LLM engine on the framework's own JAX models.

Capability mirror of the reference's vLLM engine integration (ref:
llm/_internal/serve/engines/vllm/vllm_engine.py, batch/stages/
vllm_engine_stage.py) designed for TPU/XLA rather than around CUDA:

* **Static shapes everywhere.**  The decode step is one jitted function
  over a fixed number of slots.  Prompt ingestion has two modes: the
  legacy bucketed prefill (lengths padded to powers of two — O(log
  max_seq) compiled variants) and **chunked prefill**
  (``prefill_chunk_tokens``): prompts are ingested in fixed-size chunks
  through ONE compiled `prefill_chunk` variant (slot/offset/length all
  traced), interleaved with decode steps at a configurable
  ``decode_steps_per_chunk`` ratio — a long prompt no longer
  monopolizes a step, so short-request TTFT stops queueing behind it
  and resident sessions keep decoding smoothly during ingestion.
* **Dense per-slot KV slabs** (models/llama.py `init_kv_cache`) instead
  of paged KV: XLA cannot tile dynamic gather-heavy paging the way a
  CUDA kernel can, while dense slabs keep decode attention a plain
  masked matmul on the MXU.  Slot reuse gives the same
  admit-new-work-each-step behavior as paged attention's block reuse.
* **Continuous batching**: each `step()` admits queued prompts, runs at
  most one prefill unit (a full bucketed prompt, or one chunk), then
  decodes every active slot in one batched call.
* **Session KV offload** (``session_id=`` + kv_offload.py stores): a
  finished request's slab stays RESIDENT in its slot for multi-turn
  reuse; idle sessions are evicted — LRU past ``kv_idle_evict_s`` or on
  KV-full admission pressure — by device-getting the slab to host and
  sealing it into a tiered store (object plane: arena → spill tiers),
  freeing the slot.  The next token for an offloaded session triggers a
  background-thread fetch (the step loop NEVER blocks on a restore;
  decode continues and the slab installs when it lands, attributed via
  the ``llm:restore`` trace span), making resident-session count
  disk-bounded instead of HBM-bounded.  Round trips are bitwise exact:
  restored token streams are identical to uninterrupted runs.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ant_ray_tpu.llm.sampling import SamplingParams
from ant_ray_tpu.llm.tokenizer import get_tokenizer


@dataclass
class RequestOutput:
    request_id: str
    prompt_token_ids: list
    token_ids: list = field(default_factory=list)
    text: str = ""
    finished: bool = False
    finish_reason: str | None = None
    error: str | None = None


@dataclass(eq=False)
class _Seq:
    request_id: str
    prompt: list
    sampling: SamplingParams
    slot: int = -1
    generated: list = field(default_factory=list)
    rng_key: Any = None
    session: Any = None           # _Session | None
    prefill_done: int = 0         # prompt tokens ingested (chunked mode)
    kv_len: int = 0               # slab tokens written for this slot
    last_tok: int | None = None   # device-fed token (resume after restore)
    on_event: Any = None          # callable(dict) | None — streaming sink
    trace_ctx: Any = None         # TraceContext for llm:restore spans


@dataclass(eq=False)
class _Session:
    """A logical conversation owning (at most) one KV slot over time."""

    session_id: str
    state: str = "new"            # new|resident|offloaded|restoring|failed
    slot: int = -1
    kv_len: int = 0               # tokens in the (resident or offloaded) slab
    carry: list = field(default_factory=list)  # final token, KV not written
    last_used: float = 0.0
    handle: Any = None            # offload store handle
    current: _Seq | None = None   # seq owning the slot right now
    paused: _Seq | None = None    # mid-generation seq parked by eviction
    pending: list = field(default_factory=list)  # seqs awaiting the slab


def _bucket(n: int, cap: int) -> int:
    b = 16
    while b < n:
        b *= 2
    return min(b, cap)


class LLMEngine:
    """Synchronous engine core; Serve replicas and batch stages drive it.

    ``model`` is a config name from models/llama.CONFIGS, a LOCAL
    CHECKPOINT DIRECTORY (HF Llama layout — real weights, loaded via
    models/checkpoint.py), or a LlamaConfig; ``params`` overrides both
    (random init remains the default for named configs: tests/bench).
    """

    def __init__(self, model="tiny", params=None, *, slots: int = 8,
                 max_seq: int | None = None, tokenizer=None,
                 seed: int = 0, tensor_parallel_size: int = 1,
                 mesh=None, max_waiting: int | None = None,
                 prefill_chunk_tokens: int | None = None,
                 decode_steps_per_chunk: int = 1,
                 kv_idle_evict_s: float | None = None,
                 kv_offload_store=None,
                 kv_evict_on_pressure: bool = True,
                 profiler=None):
        """``tensor_parallel_size > 1`` makes the ENGINE build a tp mesh
        over this process's local devices and shard params + KV slabs
        itself (ref: vllm_models.py:222 tensor_parallel_size — serving
        an 8B on a slice needs no caller-side sharding).  ``mesh``
        overrides it with a prebuilt mesh (e.g. tp×sp for long-prompt
        prefill via ring attention — forward() switches on sp>1).

        ``prefill_chunk_tokens``: enable chunked prefill with this fixed
        chunk width (None = legacy bucketed prefill).
        ``decode_steps_per_chunk``: decode steps run between successive
        prefill chunks while both kinds of work are pending (the
        TTFT-vs-decode-smoothness budget knob).
        ``kv_idle_evict_s``: evict a session's slab after this many
        seconds idle (None disables the LRU sweep; pressure eviction is
        governed separately by ``kv_evict_on_pressure``).
        ``kv_offload_store``: a kv_offload.py store (LocalKvStore /
        ObjectPlaneKvStore); defaults to a LocalKvStore built lazily on
        first eviction.  ``profiler``: optional StepProfiler — each
        step() records prefill/decode/restore_install phases.
        """
        from ant_ray_tpu._private.jax_utils import import_jax

        self._jax = jax = import_jax()
        import jax.numpy as jnp  # noqa: PLC0415

        self._jnp = jnp
        from ant_ray_tpu.models import llama  # noqa: PLC0415

        self._llama = llama
        loaded = None
        if isinstance(model, str):
            from ant_ray_tpu.models import checkpoint as ckpt  # noqa: PLC0415
            from ant_ray_tpu.models.llama import CONFIGS  # noqa: PLC0415

            if params is not None and model not in CONFIGS:
                # Explicit (e.g. pre-sharded) params: only the config is
                # needed — don't read gigabytes of weights to drop them.
                if not os.path.isdir(model):
                    raise ValueError(
                        f"model {model!r} is neither a named config "
                        f"{sorted(CONFIGS)} nor a local checkpoint "
                        "directory")
                self.config = ckpt.config_from_hf(model)
            else:
                loaded, self.config = ckpt.resolve_model(model)
            if tokenizer is None and model not in CONFIGS:
                tokenizer = get_tokenizer(model)  # checkpoint dir
        else:
            self.config = model
        self.max_seq = min(max_seq or self.config.max_seq,
                           self.config.max_seq)
        self.slots = slots
        self.tokenizer = tokenizer or get_tokenizer(None)
        if params is None:
            params = (loaded if loaded is not None
                      else llama.init_params(self.config,
                                             jax.random.PRNGKey(seed)))
        self.mesh = mesh
        if tensor_parallel_size > 1 and mesh is None:
            from ant_ray_tpu.parallel.mesh import build_mesh  # noqa: PLC0415

            self.mesh = build_mesh(
                devices=jax.local_devices()[:tensor_parallel_size],
                tp=tensor_parallel_size)
        self.params = params
        self.cache = llama.init_kv_cache(self.config, slots, self.max_seq)
        if self.mesh is not None:
            self._shard_state()
        # Host-side mirror of each slot's most recent token: mutated in
        # numpy and uploaded once per decode call, so the scheduling
        # loop costs one host→device transfer per step instead of one
        # tiny device op per slot.
        self._last_np = np.zeros((slots,), np.int32)
        # Admission bound: with every KV slot busy, at most this many
        # requests may wait for one (None = unbounded, legacy).  Serving
        # paths set it so a traffic spike sheds typed BackPressureError
        # at admission instead of queueing prompts toward OOM.
        self._max_waiting = max_waiting
        self._free_slots = list(range(slots))
        self._active: dict[int, _Seq] = {}        # slot -> seq
        self._waiting: list[_Seq] = []
        self._finished: list[RequestOutput] = []
        self._req_counter = itertools.count()
        self._base_key = jax.random.PRNGKey(seed ^ 0x5EED)

        # ---- chunked prefill + session state
        self._chunk_tokens = prefill_chunk_tokens
        self._decode_per_chunk = max(1, int(decode_steps_per_chunk))
        self._decode_since_chunk = self._decode_per_chunk  # 1st chunk runs now
        self._prefilling: list[_Seq] = []         # chunked-mode ingest queue
        self._sessions: dict[str, _Session] = {}
        self._kv_idle_evict_s = kv_idle_evict_s
        self._kv_evict_on_pressure = kv_evict_on_pressure
        self._kv_store = kv_offload_store
        self._restoring: dict[str, dict] = {}     # sid -> ticket
        self._chunk_rate: float | None = None     # tokens/s EWMA
        self._last_chunk_t: float | None = None
        self.profiler = profiler
        self.stats = {"tokens_generated": 0, "chunks": 0,
                      "chunk_tokens": 0, "offloads": 0,
                      "offload_bytes": 0, "restores": 0,
                      "restore_wait_s": 0.0, "restore_failures": 0,
                      "pressure_evictions": 0, "idle_evictions": 0}

        cfg = self.config
        eng_mesh = self.mesh

        def _prefill(params, cache, tokens, slot, length):
            return llama.prefill_into_cache(params, tokens, cache, slot,
                                            length, cfg, mesh=eng_mesh)

        def _prefill_chunk(params, cache, tokens, slot, start, length):
            return llama.prefill_chunk_into_cache(
                params, tokens, cache, slot, start, length, cfg)

        def _decode(params, cache, last_tokens, active):
            return llama.decode_step(params, last_tokens, cache, cfg,
                                     active=active)

        def _extract(cache, slot):
            from jax import lax  # noqa: PLC0415

            k = lax.dynamic_index_in_dim(cache["k"], slot, axis=1,
                                         keepdims=False)
            v = lax.dynamic_index_in_dim(cache["v"], slot, axis=1,
                                         keepdims=False)
            return k, v, cache["length"][slot]

        def _install(cache, k, v, length, slot):
            from jax import lax  # noqa: PLC0415

            slot = jnp.asarray(slot, jnp.int32)
            return {
                "k": lax.dynamic_update_slice(
                    cache["k"], k[:, None], (0, slot, 0, 0, 0)),
                "v": lax.dynamic_update_slice(
                    cache["v"], v[:, None], (0, slot, 0, 0, 0)),
                "length": cache["length"].at[slot].set(length),
            }

        # one compile per prompt bucket (slot/length traced); ONE chunk
        # variant (slot/start/length traced); one decode; one extract /
        # install each (slot traced).
        self._prefill_jit = jax.jit(_prefill, donate_argnums=(1,))
        self._prefill_chunk_jit = jax.jit(_prefill_chunk,
                                          donate_argnums=(1,))
        self._decode_jit = jax.jit(_decode, donate_argnums=(1,))
        self._extract_jit = jax.jit(_extract)
        self._install_jit = jax.jit(_install, donate_argnums=(0,))
        self._sample_jit = jax.jit(self._sample_batch)

    def _shard_state(self):
        """Distribute params and KV slabs over the engine's mesh: params
        by the model's logical-axis rules (heads/mlp over tp), slabs by
        kv-head over tp — decode attention then runs fully sharded with
        XLA inserting the one all-reduce per block (ref capability:
        vLLM tensor_parallel_size, engine-owned sharding)."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: PLC0415

        mesh = self.mesh
        tp = mesh.shape.get("tp", 1)
        if self.config.n_kv_heads % tp or self.config.n_heads % tp:
            raise ValueError(
                f"tensor_parallel_size={tp} must divide n_heads="
                f"{self.config.n_heads} and n_kv_heads="
                f"{self.config.n_kv_heads}")
        shardings = self._llama.param_shardings(self.config, mesh)
        self.params = jax.device_put(self.params, shardings)
        kv = NamedSharding(mesh, P(None, None, None, "tp", None))
        rep = NamedSharding(mesh, P())
        self.cache = {
            "k": jax.device_put(self.cache["k"], kv),
            "v": jax.device_put(self.cache["v"], kv),
            "length": jax.device_put(self.cache["length"], rep),
        }

    # ------------------------------------------------------------ public

    def add_request(self, prompt, sampling: SamplingParams | None = None,
                    request_id: str | None = None, *,
                    admit: bool = True, session_id: str | None = None,
                    on_event=None, trace_ctx=None) -> str:
        """prompt: str (tokenized here) or token-id list.

        With ``max_waiting`` configured and ``admit=True`` (the serving
        default), a request arriving while every KV slot is busy and the
        waiting line is full is REJECTED with
        :class:`~ant_ray_tpu.exceptions.BackPressureError` — admission
        control at the engine boundary, so overload sheds instead of
        growing an unbounded prompt queue toward OOM.  Before shedding,
        an idle resident session is evicted to the offload store if one
        exists (``kv_evict_on_pressure``) — pressure admits new work by
        spilling cold state instead of refusing.  The retry hint derives
        from the measured chunk-drain rate.  Offline batch paths
        (``generate``) pass ``admit=False``: a caller handing the
        engine a fixed batch wants queueing.

        ``session_id`` attaches the request to a persistent session: its
        KV slab survives the request (multi-turn reuse; continuations
        require chunked mode) and may be offloaded/restored.
        ``on_event`` streams per-token dicts to the caller (EngineLoop's
        sink); ``trace_ctx`` attributes `llm:restore` spans."""
        if (admit and self._max_waiting is not None
                and not self._free_slots
                and len(self._waiting) >= self._max_waiting
                and not self._evict_for_pressure()):
            from ant_ray_tpu.exceptions import BackPressureError  # noqa: PLC0415

            raise BackPressureError(
                f"engine at capacity: {self.slots} KV slots busy, "
                f"{len(self._waiting)} waiting (max_waiting="
                f"{self._max_waiting})",
                retry_after_s=self.retry_after_hint())
        sampling = sampling or SamplingParams()
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
        else:
            token_ids = list(prompt)
        if not token_ids:
            raise ValueError("empty prompt")
        budget = max(1, self.max_seq - sampling.max_tokens)
        if len(token_ids) > budget:
            token_ids = token_ids[-budget:]      # keep the suffix
        rid = request_id or f"req-{next(self._req_counter)}"
        seq = _Seq(rid, token_ids, sampling)
        seq.on_event = on_event
        seq.trace_ctx = trace_ctx
        if session_id is not None:
            sess = self._sessions.get(session_id)
            if sess is None or sess.state == "failed":
                sess = _Session(session_id)
                self._sessions[session_id] = sess
            elif self._chunk_tokens is None:
                # Any reuse, not just kv_len > 0: a continuation queued
                # while turn 1 is still in flight (kv_len still 0 here)
                # would otherwise reach _admit with a slab offset the
                # bucketed kernel cannot append at.
                raise ValueError(
                    "session continuation requires chunked prefill "
                    "(prefill_chunk_tokens=) — bucketed prefill cannot "
                    "append at a slab offset")
            seq.session = sess
        seed = sampling.seed
        key = (self._jax.random.PRNGKey(seed) if seed is not None
               else self._jax.random.fold_in(self._base_key, hash(rid)
                                             & 0x7FFFFFFF))
        seq.rng_key = key
        self._waiting.append(seq)
        return rid

    def has_unfinished(self) -> bool:
        return bool(self._waiting or self._active or self._prefilling
                    or self._restoring
                    or any(s.paused or s.pending
                           for s in self._sessions.values()))

    def step(self) -> list[RequestOutput]:
        """One engine iteration: land finished restores, admit prompts,
        run one prefill unit (bucketed prompt or one chunk), decode all
        active slots, sweep idle sessions.  Returns outputs finished
        since the last call."""
        prof = self.profiler
        if prof is not None:
            with prof.step():
                self._step_inner(prof)
        else:
            self._step_inner(None)
        done, self._finished = self._finished, []
        return done

    def _step_inner(self, prof):
        self._poll_restores(prof)
        self._admit(prof)
        if self._chunk_tokens is not None:
            self._maybe_prefill_chunk(prof)
        self._decode(prof)
        self._sweep_idle()

    def generate(self, prompts, sampling: SamplingParams | None = None,
                 ) -> list[RequestOutput]:
        """Run a batch of prompts to completion (offline inference)."""
        order = [self.add_request(p, sampling, admit=False)
                 for p in prompts]
        outputs: dict[str, RequestOutput] = {}
        while self.has_unfinished():
            for out in self.step():
                outputs[out.request_id] = out
        return [outputs[rid] for rid in order]

    def stream(self, prompt, sampling: SamplingParams | None = None):
        """Incremental generation for one request: yields a dict per new
        token ({"token_id", "text", "finished": False}) and a final
        summary chunk ({"finished": True, "finish_reason", "token_ids",
        "full_text"}) — the serving-side source for SSE token streaming
        (ref capability: vllm engine streaming outputs)."""
        rid = self.add_request(prompt, sampling)
        seq = self._waiting[-1]
        assert seq.request_id == rid
        emitted = 0
        final: RequestOutput | None = None
        while final is None and self.has_unfinished():
            for out in self.step():
                if out.request_id == rid:
                    final = out
            source = final.token_ids if final else seq.generated
            while emitted < len(source):
                tok = int(source[emitted])
                emitted += 1
                yield {"token_id": tok,
                       "text": self.tokenizer.decode([tok]),
                       "finished": False,
                       "finish_reason": None}
        yield {"token_id": None,
               "text": "",
               "finished": True,
               "finish_reason": (final.finish_reason if final
                                 else "length"),
               "token_ids": list(final.token_ids) if final else [],
               "full_text": final.text if final else ""}

    # -------------------------------------------------- sessions public

    def resident_sessions(self) -> int:
        """Live sessions the engine is holding KV state for — resident,
        offloaded, or mid-restore.  Exceeds ``slots`` exactly when
        offload is doing its job."""
        return sum(1 for s in self._sessions.values()
                   if s.state in ("resident", "offloaded", "restoring"))

    def queue_depth(self) -> int:
        """Requests admitted but not yet generating: waiting for a slot,
        mid-prefill, or parked behind a session restore."""
        return (len(self._waiting) + len(self._prefilling)
                + sum(len(s.pending) + (1 if s.paused else 0)
                      for s in self._sessions.values()))

    def chunk_drain_rate(self) -> float | None:
        """Measured prefill-chunk throughput (tokens/s EWMA), the basis
        for KV-full retry hints.  None until the first two chunks."""
        return self._chunk_rate

    def retry_after_hint(self) -> float:
        """BackPressure retry hint: outstanding prompt tokens over the
        measured chunk-drain rate (legacy fallback: 0.5 s)."""
        rate = self._chunk_rate
        if not rate or rate <= 0:
            return 0.5
        outstanding = sum(max(0, len(s.prompt) - s.prefill_done)
                          for s in self._prefilling)
        outstanding += sum(len(s.prompt) for s in self._waiting)
        outstanding += self._chunk_tokens or 0   # the admitted request
        return min(30.0, max(0.05, outstanding / rate + 0.02))

    def evict_session(self, session_id: str, *, force: bool = False
                      ) -> bool:
        """Offload one session's slab now.  Idle sessions always
        qualify; ``force=True`` additionally pauses a mid-GENERATION
        session (its request resumes after an automatic restore —
        bit-identically, since the slab round trip is exact).  Sessions
        mid-prefill are never evictable.  Returns True if evicted."""
        sess = self._sessions.get(session_id)
        if sess is None or sess.state != "resident" or sess.slot < 0:
            return False
        cur = sess.current
        if cur is not None:
            if not force or cur in self._prefilling:
                return False
            self._active.pop(cur.slot, None)
            cur.slot = -1
            sess.paused = cur
            sess.current = None
        self._offload(sess)
        return True

    def end_session(self, session_id: str) -> bool:
        """Drop a session: frees its slot (if resident) and deletes its
        offloaded slab (if any).  In-flight work is not interrupted —
        call only for idle sessions."""
        sess = self._sessions.pop(session_id, None)
        if sess is None:
            return False
        if sess.slot >= 0 and sess.current is None:
            self._free_slots.append(sess.slot)
            sess.slot = -1
        if sess.handle is not None and self._kv_store is not None:
            try:
                self._kv_store.delete(sess.handle)
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        return True

    def has_evictable(self) -> bool:
        """True if admission pressure could free a slot by evicting an
        idle resident session (the submit-side gate's cheap probe)."""
        return any(s.state == "resident" and s.slot >= 0
                   and s.current is None and s.paused is None
                   for s in self._sessions.values())

    # ---------------------------------------------------- step phases

    def _admit(self, prof=None):
        """Route waiting requests: park session continuations behind
        restores, assign free (or pressure-evicted) slots, and in
        legacy mode run at most one full bucketed prefill per step —
        the budget covers BOTH the resident-idle-session branch and the
        fresh-slot branch."""
        # Sessions parked with work but offloaded: ensure a restore is
        # in flight (covers forced mid-generation eviction).
        for sess in self._sessions.values():
            if sess.state == "offloaded" and (sess.paused or sess.pending):
                self._start_restore(sess)
        admitted_prefill = False
        i = 0
        while i < len(self._waiting):
            seq = self._waiting[i]
            sess = seq.session
            if sess is not None and sess.state in ("offloaded",
                                                   "restoring"):
                self._waiting.pop(i)
                sess.pending.append(seq)
                if sess.state == "offloaded":
                    self._start_restore(sess)
                continue
            if sess is not None and sess.slot >= 0 and (
                    sess.current is not None or sess.paused is not None):
                self._waiting.pop(i)          # session busy: park
                sess.pending.append(seq)
                continue
            if sess is not None and sess.slot >= 0:
                if self._chunk_tokens is None and admitted_prefill:
                    break                     # legacy: ≤1 prefill/step
                self._waiting.pop(i)          # resident idle: append
                self._begin_ingest(seq, sess.slot, sess.kv_len, prof)
                admitted_prefill = True
                continue
            if not self._free_slots and not self._evict_for_pressure():
                i += 1
                continue
            if self._chunk_tokens is None and admitted_prefill:
                break                         # legacy: ≤1 prefill/step
            slot = self._free_slots.pop()
            self._waiting.pop(i)
            if sess is not None:
                sess.slot = slot
                sess.state = "resident"
            self._begin_ingest(seq, slot, sess.kv_len if sess else 0,
                               prof)
            admitted_prefill = True

    def _begin_ingest(self, seq: _Seq, slot: int, start: int, prof=None):
        jnp = self._jnp
        sess = seq.session
        if self._chunk_tokens is None and start != 0:
            # add_request rejects bucketed session continuations, so
            # this is a backstop: fail the one seq typed (the session
            # keeps its resident slot, idle) — raising mid-step would
            # leave the seq in no queue and wedge its caller's wait().
            self._fail_seq(seq, ValueError(
                "bucketed prefill cannot continue a session at offset "
                f"{start}; configure prefill_chunk_tokens"))
            return
        if sess is not None:
            sess.current = seq
            sess.last_used = time.monotonic()
            if sess.carry:
                seq.prompt = sess.carry + seq.prompt
                sess.carry = []
        seq.slot = slot
        seq.kv_len = start
        if self._chunk_tokens is not None:
            self._prefilling.append(seq)
            return
        bucket = _bucket(len(seq.prompt), self.max_seq)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(seq.prompt)] = seq.prompt
        timer = prof.phase("prefill") if prof is not None else _NOOP_TIMER
        with timer:
            last_logits, self.cache = self._prefill_jit(
                self.params, self.cache, jnp.asarray(padded), slot,
                len(seq.prompt))
        seq.kv_len = len(seq.prompt)
        tok = int(self._sample_one(seq, last_logits))
        self._after_token(seq, tok)
        if seq.slot >= 0:
            seq.last_tok = tok
            self._last_np[slot] = tok
            self._active[slot] = seq

    def _maybe_prefill_chunk(self, prof=None):
        """Run ONE chunk of ONE pending prompt — but only once
        ``decode_steps_per_chunk`` decode steps have run since the last
        chunk (decode for resident sessions stays smooth while a long
        prompt trickles in).

        Selection is shortest-remaining-prompt-first (FIFO tiebreak):
        a short interactive prompt's single chunk jumps ahead of a
        long ingest's remaining hundreds, so short TTFT stays flat
        under long-prompt interference.  Long prompts cannot starve —
        they absorb every chunk slot no short is contending for — but
        a sustained flood of short prompts will stall them; that is
        the intended bias for an interactive serving tier."""
        if not self._prefilling:
            return
        if self._active and \
                self._decode_since_chunk < self._decode_per_chunk:
            return
        jnp = self._jnp
        idx = min(range(len(self._prefilling)),
                  key=lambda i: (len(self._prefilling[i].prompt)
                                 - self._prefilling[i].prefill_done, i))
        seq = self._prefilling.pop(idx)
        chunk = self._chunk_tokens
        part = seq.prompt[seq.prefill_done:seq.prefill_done + chunk]
        buf = np.zeros((chunk,), np.int32)
        buf[:len(part)] = part
        timer = prof.phase("prefill") if prof is not None else _NOOP_TIMER
        with timer:
            logits, self.cache = self._prefill_chunk_jit(
                self.params, self.cache, jnp.asarray(buf), seq.slot,
                seq.kv_len, len(part))
        seq.prefill_done += len(part)
        seq.kv_len += len(part)
        self._note_chunk(len(part))
        self._decode_since_chunk = 0
        if seq.prefill_done < len(seq.prompt):
            self._prefilling.append(seq)
            return
        tok = int(self._sample_one(seq, logits))
        self._after_token(seq, tok)
        if seq.slot >= 0:
            seq.last_tok = tok
            self._last_np[seq.slot] = tok
            self._active[seq.slot] = seq

    def _decode(self, prof=None):
        if not self._active:
            return
        jnp = self._jnp
        mask = np.zeros((self.slots,), bool)
        mask[list(self._active)] = True
        timer = prof.phase("decode") if prof is not None else _NOOP_TIMER
        with timer:
            logits, self.cache = self._decode_jit(
                self.params, self.cache, jnp.asarray(self._last_np),
                jnp.asarray(mask))
            toks = np.asarray(self._sample_all(logits))
        self._decode_since_chunk += 1
        for slot, seq in list(self._active.items()):
            # this call wrote seq.last_tok's K/V at position kv_len
            seq.kv_len = min(seq.kv_len + 1, self.max_seq)
            tok = int(toks[slot])
            self.stats["tokens_generated"] += 1
            self._after_token(seq, tok)
            if seq.slot >= 0:
                seq.last_tok = tok
                self._last_np[slot] = tok

    def _note_chunk(self, n: int):
        self.stats["chunks"] += 1
        self.stats["chunk_tokens"] += n
        now = time.monotonic()
        if self._last_chunk_t is not None:
            dt = max(now - self._last_chunk_t, 1e-6)
            inst = n / dt
            self._chunk_rate = (inst if self._chunk_rate is None
                                else 0.8 * self._chunk_rate + 0.2 * inst)
        self._last_chunk_t = now

    # ------------------------------------------------- offload/restore

    def _store(self):
        if self._kv_store is None:
            from ant_ray_tpu.llm.kv_offload import LocalKvStore  # noqa: PLC0415

            self._kv_store = LocalKvStore()
        return self._kv_store

    def _evict_for_pressure(self) -> bool:
        """Free one slot by offloading the least-recently-used IDLE
        resident session.  Admission pressure spills cold state instead
        of shedding new work."""
        if not self._kv_evict_on_pressure:
            return False
        idle = [s for s in self._sessions.values()
                if s.state == "resident" and s.slot >= 0
                and s.current is None and s.paused is None]
        if not idle:
            return False
        victim = min(idle, key=lambda s: s.last_used)
        self._offload(victim)
        self.stats["pressure_evictions"] += 1
        return True

    def _sweep_idle(self):
        if self._kv_idle_evict_s is None:
            return
        cutoff = time.monotonic() - self._kv_idle_evict_s
        for sess in list(self._sessions.values()):
            if (sess.state == "resident" and sess.slot >= 0
                    and sess.current is None and sess.paused is None
                    and sess.last_used < cutoff):
                self._offload(sess)
                self.stats["idle_evictions"] += 1

    def _offload(self, sess: _Session):
        """Device-get the session's slab and seal it into the offload
        store; the slot returns to the free pool.  The slab is NOT
        zeroed — stale bytes past a future occupant's length are masked
        exactly like reused slots always were."""
        slot = sess.slot
        k, v, ln = self._extract_jit(self.cache, slot)
        slab = (np.asarray(k), np.asarray(v), int(ln))
        sess.handle = self._store().put(sess.session_id, slab)
        sess.kv_len = int(ln)
        sess.slot = -1
        sess.state = "offloaded"
        self._free_slots.append(slot)
        self.stats["offloads"] += 1
        self.stats["offload_bytes"] += (slab[0].nbytes + slab[1].nbytes)

    def _start_restore(self, sess: _Session):
        if sess.state != "offloaded":
            return
        sess.state = "restoring"
        ticket = {"done": False, "result": None, "error": None,
                  "t0": time.monotonic(), "wall0": time.time()}
        self._restoring[sess.session_id] = ticket
        store, handle = self._store(), sess.handle

        def fetch():
            try:
                ticket["result"] = store.get(handle)
            except BaseException as exc:  # noqa: BLE001 — typed below
                ticket["error"] = exc
            finally:
                ticket["done"] = True

        threading.Thread(target=fetch, daemon=True,
                         name=f"kv-restore-{sess.session_id}").start()

    def _poll_restores(self, prof=None):
        """Land finished restore fetches: install the slab into a free
        (or pressure-evicted) slot and resume the session's work.  Never
        blocks — unfinished fetches stay in flight while decode
        proceeds; a landed fetch with no slot available retries next
        step."""
        if not self._restoring:
            return
        jnp = self._jnp
        for sid, ticket in list(self._restoring.items()):
            if not ticket["done"]:
                continue
            sess = self._sessions.get(sid)
            if sess is None:
                del self._restoring[sid]
                continue
            if ticket["error"] is not None:
                del self._restoring[sid]
                self._fail_session(sess, ticket["error"], ticket)
                continue
            if not self._free_slots and not self._evict_for_pressure():
                continue                     # retry next step
            slot = self._free_slots.pop()
            del self._restoring[sid]
            k, v, ln = ticket["result"]
            timer = (prof.phase("restore_install") if prof is not None
                     else _NOOP_TIMER)
            with timer:
                self.cache = self._install_jit(
                    self.cache, jnp.asarray(k), jnp.asarray(v),
                    jnp.int32(ln), slot)
            dur = time.monotonic() - ticket["t0"]
            self.stats["restores"] += 1
            self.stats["restore_wait_s"] += dur
            self._record_restore_span(sess, ticket, dur,
                                      k.nbytes + v.nbytes)
            sess.slot = slot
            sess.state = "resident"
            sess.kv_len = int(ln)
            sess.last_used = time.monotonic()
            if sess.paused is not None:
                seq = sess.paused
                sess.paused = None
                sess.current = seq
                seq.slot = slot
                self._last_np[slot] = seq.last_tok
                self._active[slot] = seq
            elif sess.pending:
                self._begin_ingest(sess.pending.pop(0), slot,
                                   sess.kv_len, prof)

    def _record_restore_span(self, sess: _Session, ticket: dict,
                             dur: float, nbytes: int):
        """Attribute the restore to the request that paid for it via the
        PR 8 trace plane (`llm:restore`), on whichever seq carries a
        trace context."""
        seq = sess.paused or (sess.pending[0] if sess.pending else None)
        ctx = seq.trace_ctx if seq is not None else None
        if ctx is None:
            return
        try:
            from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

            tracing_plane.record_span(
                ctx, "llm:restore", ts=ticket["wall0"], dur_s=dur,
                attrs={"session": sess.session_id, "bytes": nbytes})
        except Exception:  # noqa: BLE001 — tracing is best-effort
            pass

    def _fail_session(self, sess: _Session, exc, ticket: dict):
        """A restore failed (e.g. holder died mid-pull): fail THIS
        session's requests typed and reset the session record; every
        other slot keeps decoding — the loop never wedges."""
        from ant_ray_tpu.exceptions import KVRestoreError  # noqa: PLC0415

        self.stats["restore_failures"] += 1
        err = KVRestoreError(
            f"session {sess.session_id!r}: KV restore failed: {exc!r}",
            session_id=sess.session_id)
        seqs = ([sess.paused] if sess.paused else []) + sess.pending
        sess.paused = None
        sess.pending = []
        sess.state = "failed"
        sess.handle = None
        sess.kv_len = 0
        seq0 = seqs[0] if seqs else None
        if seq0 is not None and seq0.trace_ctx is not None:
            try:
                from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

                tracing_plane.record_span(
                    seq0.trace_ctx, "llm:restore", ts=ticket["wall0"],
                    dur_s=time.monotonic() - ticket["t0"], error=True,
                    attrs={"session": sess.session_id,
                           "error": repr(exc)})
            except Exception:  # noqa: BLE001
                pass
        for seq in seqs:
            self._fail_seq(seq, err)

    def _fail_seq(self, seq: _Seq, err):
        out = RequestOutput(
            request_id=seq.request_id, prompt_token_ids=seq.prompt,
            token_ids=list(seq.generated),
            text=self.tokenizer.decode(seq.generated),
            finished=True, finish_reason="error", error=str(err))
        self._finished.append(out)
        if seq.on_event is not None:
            seq.on_event({"type": "error", "error": err, "output": out})

    # ----------------------------------------------------------- private

    def _after_token(self, seq: _Seq, tok: int):
        seq.generated.append(tok)
        s = seq.sampling
        eos = getattr(self.tokenizer, "eos_id",
                      getattr(self.tokenizer, "eos_token_id", None))
        stop = set(s.stop_token_ids)
        if eos is not None:
            stop.add(int(eos))
        reason = None
        if tok in stop:
            reason = "stop"
        elif len(seq.generated) >= s.max_tokens:
            reason = "length"
        elif seq.kv_len + 1 >= self.max_seq:
            reason = "length"
        if seq.on_event is not None and reason != "stop":
            seq.on_event({"type": "token", "token_id": tok})
        if reason is not None:
            self._release(seq, reason)

    def _release(self, seq: _Seq, reason: str):
        out_ids = (seq.generated[:-1] if reason == "stop"
                   else seq.generated)
        out = RequestOutput(
            request_id=seq.request_id,
            prompt_token_ids=seq.prompt,
            token_ids=list(out_ids),
            text=self.tokenizer.decode(out_ids),
            finished=True,
            finish_reason=reason,
        )
        self._finished.append(out)
        sess = seq.session
        if seq.slot >= 0:
            self._active.pop(seq.slot, None)
            if sess is None:
                self._free_slots.append(seq.slot)
            else:
                # Slot stays with the session (multi-turn KV reuse).
                # The final token's K/V was never written — carry it
                # into the next turn's ingest.
                sess.kv_len = seq.kv_len
                sess.carry = list(seq.generated[-1:])
                sess.current = None
                sess.last_used = time.monotonic()
            seq.slot = -1
        elif sess is not None and sess.current is seq:
            sess.current = None
            sess.last_used = time.monotonic()
        if sess is not None and sess.pending and sess.slot >= 0 \
                and sess.current is None and sess.paused is None:
            # Next turn already queued: put it at the head of the line.
            self._waiting.insert(0, sess.pending.pop(0))
        if seq.on_event is not None:
            seq.on_event({"type": "final", "output": out})

    def _sample_one(self, seq: _Seq, logits):
        seq.rng_key, sub = self._jax.random.split(seq.rng_key)
        s = seq.sampling
        return self._sample_jit(
            logits[None], sub[None],
            self._jnp.asarray([s.temperature], self._jnp.float32),
            self._jnp.asarray([s.top_k], self._jnp.int32),
            self._jnp.asarray([s.top_p], self._jnp.float32))[0]

    def _sample_all(self, logits):
        jnp = self._jnp
        temps = np.zeros((self.slots,), np.float32)
        top_ks = np.zeros((self.slots,), np.int32)
        top_ps = np.ones((self.slots,), np.float32)
        keys = np.zeros((self.slots, 2), np.uint32)
        for slot, seq in self._active.items():
            s = seq.sampling
            temps[slot] = s.temperature
            top_ks[slot] = s.top_k
            top_ps[slot] = s.top_p
            seq.rng_key, sub = self._jax.random.split(seq.rng_key)
            keys[slot] = np.asarray(sub)
        return self._sample_jit(
            logits, jnp.asarray(keys), jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps))

    def _sample_batch(self, logits, keys, temps, top_ks, top_ps):
        """Vectorized per-slot sampling: greedy when temperature == 0,
        else temperature softmax with optional top-k / top-p (nucleus)
        filtering — all branch-free for XLA."""
        jax, jnp = self._jax, self._jnp
        vocab = logits.shape[-1]
        greedy = jnp.argmax(logits, axis=-1)

        scaled = logits / jnp.maximum(temps[:, None], 1e-6)
        # top-k: mask everything below the k-th largest (k==0 → keep all)
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        k_idx = jnp.clip(top_ks - 1, 0, vocab - 1)
        kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
        keep_k = (top_ks[:, None] <= 0) | (scaled >= kth)
        # top-p: smallest prefix of the sorted distribution with
        # cumulative prob >= p
        probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs_sorted, axis=-1)
        cutoff_rank = jnp.sum(cum < top_ps[:, None], axis=-1)  # inclusive
        ranks = jnp.argsort(jnp.argsort(-scaled, axis=-1), axis=-1)
        keep_p = ranks <= cutoff_rank[:, None]
        masked = jnp.where(keep_k & keep_p, scaled, -jnp.inf)
        sampled = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg))(keys, masked)
        return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


class _NoopTimer:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_TIMER = _NoopTimer()


class _LoopHandle:
    """Per-request handle returned by :meth:`EngineLoop.submit`: an
    event queue for streaming plus a wait() for the final output."""

    def __init__(self, request_id: str):
        import queue as _q  # noqa: PLC0415

        self.request_id = request_id
        self.events = _q.Queue()
        self.submit_ts = time.monotonic()
        self.first_token_ts: float | None = None
        self._final: RequestOutput | None = None
        self._error: BaseException | None = None
        self._done = threading.Event()

    # engine-loop side ------------------------------------------------
    def _on_event(self, ev: dict):
        if ev["type"] == "token" and self.first_token_ts is None:
            self.first_token_ts = time.monotonic()
        if ev["type"] == "final":
            self._final = ev["output"]
        elif ev["type"] == "error":
            self._error = ev["error"]
            self._final = ev.get("output")
        self.events.put(ev)
        if ev["type"] in ("final", "error"):
            self._done.set()

    def _fail(self, exc: BaseException):
        self._on_event({"type": "error", "error": exc, "output": None})

    # caller side -----------------------------------------------------
    def wait(self, timeout: float | None = None) -> RequestOutput:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._final

    def ttft_s(self) -> float | None:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.submit_ts

    def __iter__(self):
        """Yield events until (and including) the final/error event."""
        while True:
            ev = self.events.get()
            yield ev
            if ev["type"] in ("final", "error"):
                return


class EngineLoop:
    """Background stepper that OWNS an engine: requests are submitted
    from any thread; one loop thread interleaves chunked prefill,
    decode, and restore landing, and streams tokens to per-request
    sinks.  This replaces the old request-holds-the-engine-lock serving
    model — TTFT isolation requires concurrent requests to share steps,
    not serialize whole generations.

    The loop also publishes the serve-autoscaling load gauges
    (``art_llm_tokens_per_s``, ``art_llm_queue_depth``,
    ``art_llm_resident_sessions``) and exposes them via
    :meth:`load_signals` for controller polling."""

    METRIC_NAMES = ("art_llm_tokens_per_s", "art_llm_queue_depth",
                    "art_llm_resident_sessions")

    def __init__(self, engine: LLMEngine, *,
                 max_waiting: int | None = None,
                 deployment: str = "llm",
                 metrics_interval_s: float = 2.0,
                 idle_sleep_s: float = 0.01):
        self._engine = engine
        self._max_waiting = (max_waiting if max_waiting is not None
                             else engine._max_waiting)
        self._deployment = deployment
        self._metrics_interval = metrics_interval_s
        self._idle_sleep = idle_sleep_s
        self._inbox: list = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._tokens_per_s = 0.0
        self._last_tick = time.monotonic()
        self._last_tokens = 0
        self._gauges = None
        self._snapshot = self._loop_snapshot(engine)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="llm-engine-loop")
        self._thread.start()

    # ------------------------------------------------------- submission

    def submit(self, prompt, sampling: SamplingParams | None = None, *,
               session_id: str | None = None,
               request_id: str | None = None,
               trace_ctx=None) -> _LoopHandle:
        """Admission-gate and enqueue one request; returns its handle.

        Sheds typed BackPressureError when the engine is KV-full (no
        free slot, nothing evictable) and the waiting line is at
        ``max_waiting`` — with the retry hint derived from the measured
        chunk-drain rate."""
        eng = self._engine
        if self._max_waiting is not None:
            with self._lock:
                inbox_n = len(self._inbox)
            # Requests waiting for a SLOT (mid-prefill seqs hold theirs
            # already and don't count against the line).  List len()
            # reads are GIL-atomic, so _waiting/_free_slots stay live;
            # the SESSION-map walks (parked count, evictability) come
            # from the loop-published snapshot — iterating _sessions
            # from this thread could blow up mid-resize.  Snapshot
            # staleness costs at most a spurious/missed 429 for one
            # request, never corruption.
            snap = self._snapshot
            waiting = inbox_n + len(eng._waiting) + snap["parked"]
            if (waiting >= self._max_waiting and not eng._free_slots
                    and not snap["evictable"]):
                from ant_ray_tpu.exceptions import BackPressureError  # noqa: PLC0415

                raise BackPressureError(
                    f"llm engine at capacity: {eng.slots} KV slots "
                    f"busy, {waiting} waiting (max_waiting="
                    f"{self._max_waiting})",
                    retry_after_s=eng.retry_after_hint())
        rid = request_id or f"req-{next(eng._req_counter)}"
        handle = _LoopHandle(rid)
        with self._lock:
            self._inbox.append((prompt, sampling, rid, session_id,
                                trace_ctx, handle))
        self._wake.set()
        return handle

    def _call_on_loop(self, fn, timeout: float = 30.0):
        """Run ``fn(engine)`` on the loop thread and return its result
        (None on timeout).  Every mutation of the engine's session /
        slot maps must go through here — the loop thread owns them."""
        done = threading.Event()
        res = {}

        def op(eng):
            try:
                res["val"] = fn(eng)
            finally:
                done.set()

        with self._lock:
            self._inbox.append(("__op__", op, None, None, None, None))
        self._wake.set()
        done.wait(timeout)
        return res.get("val")

    def evict_session(self, session_id: str, *, force: bool = False
                      ) -> bool:
        """Thread-safe wrapper: the eviction runs on the loop thread."""
        return bool(self._call_on_loop(
            lambda eng: eng.evict_session(session_id, force=force)))

    def end_session(self, session_id: str) -> bool:
        """Thread-safe wrapper: the teardown runs on the loop thread —
        end_session frees slots and drops session records, which would
        race the stepper if called from a replica/request thread."""
        return bool(self._call_on_loop(
            lambda eng: eng.end_session(session_id)))

    # ---------------------------------------------------------- signals

    @staticmethod
    def _loop_snapshot(eng: LLMEngine) -> dict:
        """Admission/load counters as one fresh dict, published by the
        loop thread each iteration: submit() and stats() read THIS
        instead of walking the live engine structures (which the loop
        mutates concurrently — cross-thread iteration can blow up
        mid-resize).  At worst one step stale: a bounded gauge blip."""
        return {
            "parked": sum(len(s.pending) + (1 if s.paused else 0)
                          for s in eng._sessions.values()),
            "evictable": eng.has_evictable(),
            "queue_depth": eng.queue_depth(),
            "resident_sessions": eng.resident_sessions(),
        }

    def stats(self) -> dict:
        snap = self._snapshot
        return {
            "art_llm_tokens_per_s": self._tokens_per_s,
            "art_llm_queue_depth": float(snap["queue_depth"]),
            "art_llm_resident_sessions":
                float(snap["resident_sessions"]),
        }

    load_signals = stats

    def shutdown(self, timeout: float = 5.0):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout)

    # ------------------------------------------------------------- loop

    def _drain_inbox(self, eng):
        with self._lock:
            items, self._inbox = self._inbox, []
        for prompt, sampling, rid, session_id, trace_ctx, handle in items:
            if prompt == "__op__":
                sampling(eng)             # an injected loop-thread op
                continue
            try:
                eng.add_request(prompt, sampling, rid, admit=False,
                                session_id=session_id,
                                on_event=handle._on_event,
                                trace_ctx=trace_ctx)
            except BaseException as exc:  # noqa: BLE001 — typed to caller
                handle._fail(exc)

    def _run(self):
        eng = self._engine
        while not self._stop:
            self._drain_inbox(eng)
            if eng.has_unfinished():
                try:
                    eng.step()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    import logging  # noqa: PLC0415

                    logging.getLogger(__name__).exception(
                        "llm engine step failed")
                    time.sleep(0.05)
            else:
                self._wake.wait(self._idle_sleep)
                self._wake.clear()
            self._snapshot = self._loop_snapshot(eng)
            now = time.monotonic()
            if now - self._last_tick >= self._metrics_interval:
                self._tick_metrics(eng, now)

    def _tick_metrics(self, eng, now: float):
        tokens = eng.stats["tokens_generated"]
        dt = max(now - self._last_tick, 1e-6)
        self._tokens_per_s = (tokens - self._last_tokens) / dt
        self._last_tokens = tokens
        self._last_tick = now
        try:
            if self._gauges is None:
                from ant_ray_tpu.util.metrics import Gauge  # noqa: PLC0415

                self._gauges = {
                    name: Gauge(name, tag_keys=("deployment",))
                    for name in self.METRIC_NAMES}
            tags = {"deployment": self._deployment}
            for name, value in self.stats().items():
                self._gauges[name].set(value, tags)
        except Exception:  # noqa: BLE001 — metrics are best-effort
            pass
