"""Tokenizers for the LLM layer.

``get_tokenizer(name)`` loads a HuggingFace tokenizer when the
``transformers`` package and the named model are available (the
reference delegates tokenization to the engine's HF tokenizer); the
dependency-free :class:`ByteTokenizer` covers tests and air-gapped use.
"""

from __future__ import annotations


class ByteTokenizer:
    """UTF-8 bytes as token ids; bos/eos reserved at the top of the
    byte range so it fits any vocab >= 256."""

    bos_id = 254
    eos_id = 255

    @property
    def vocab_size(self) -> int:
        return 256

    def encode(self, text: str) -> list[int]:
        return [b if b < 254 else 253 for b in text.encode("utf-8")]

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= int(i) < 254)
        return data.decode("utf-8", errors="replace")


def get_tokenizer(name_or_path: str | None):
    """HF tokenizer when available, ByteTokenizer otherwise/for None."""
    if not name_or_path:
        return ByteTokenizer()
    try:
        from transformers import AutoTokenizer  # noqa: PLC0415

        return AutoTokenizer.from_pretrained(name_or_path)
    except Exception:  # noqa: BLE001 — offline / unknown model
        return ByteTokenizer()
