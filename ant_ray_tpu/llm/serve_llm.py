"""Serve deployment wrapping the LLM engine (capability mirror of the
reference's OpenAI-compatible serving layer, ref: llm/_internal/serve/
deployments/ + serve/llm/).

``build_llm_deployment`` returns a serve Application; each replica owns
one engine driven by a background :class:`EngineLoop` — concurrent
requests SHARE engine steps (chunked prefill interleaved with decode)
instead of serializing whole generations behind a lock, which is what
gives short requests TTFT isolation from long prompts.  The
request/response dicts follow the OpenAI completions shape (``prompt``
→ ``choices[].text``) so a client of the reference's `ray.serve.llm`
finds the same surface.

Session affinity: a request carrying ``session_id`` keeps its KV slab
across turns on THIS replica (idle slabs offload to the tiered object
store and restore transparently).  Multi-replica session routing rides
the future owner-direct call plane (ROADMAP item 2) — until then, pin
sessions to a replica via handle affinity or num_replicas=1.
"""

from __future__ import annotations

import time

from ant_ray_tpu.llm.engine import EngineLoop, LLMEngine
from ant_ray_tpu.llm.sampling import SamplingParams


class LLMServer:
    """Replica class: one engine + one background engine loop."""

    def __init__(self, model="tiny", *, slots: int = 8,
                 max_seq: int | None = None, tokenizer_name: str | None =
                 None, seed: int = 0, tensor_parallel_size: int = 1,
                 max_waiting: int | None = None,
                 prefill_chunk_tokens: int | None = 64,
                 decode_steps_per_chunk: int = 1,
                 kv_idle_evict_s: float | None = None,
                 kv_offload="auto"):
        from ant_ray_tpu.llm.tokenizer import get_tokenizer  # noqa: PLC0415

        store = self._resolve_store(kv_offload)
        self.engine = LLMEngine(
            model, slots=slots, max_seq=max_seq,
            tokenizer=get_tokenizer(tokenizer_name), seed=seed,
            tensor_parallel_size=tensor_parallel_size,
            max_waiting=max_waiting,
            prefill_chunk_tokens=prefill_chunk_tokens,
            decode_steps_per_chunk=decode_steps_per_chunk,
            kv_idle_evict_s=kv_idle_evict_s,
            kv_offload_store=store)
        self._loop = EngineLoop(self.engine, max_waiting=max_waiting)

    @staticmethod
    def _resolve_store(kv_offload):
        """"auto" → object plane when this process is a cluster worker,
        host-local otherwise; "object"/"local" force a tier; a store
        instance passes through; None lets the engine default apply."""
        if kv_offload is None or not isinstance(kv_offload, str):
            return kv_offload
        from ant_ray_tpu.llm import kv_offload as kvo  # noqa: PLC0415

        if kv_offload == "local":
            return kvo.LocalKvStore()
        if kv_offload == "object":
            return kvo.ObjectPlaneKvStore()
        if kv_offload == "auto":
            try:
                from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

                if global_worker.connected:
                    return kvo.ObjectPlaneKvStore()
            except Exception:  # noqa: BLE001 — no runtime: local tier
                pass
            return kvo.LocalKvStore()
        raise ValueError(f"unknown kv_offload mode {kv_offload!r}")

    @staticmethod
    def _check_deadline(where: str) -> None:
        """Shed a request whose end-to-end deadline (stamped by the
        serve ingress/handle) already expired — generating tokens
        nobody is waiting for would burn engine steps for nothing."""
        from ant_ray_tpu.exceptions import DeadlineExceededError  # noqa: PLC0415
        from ant_ray_tpu.serve.api import get_request_deadline  # noqa: PLC0415

        deadline_ts = get_request_deadline()  # wall-clock wire field
        if deadline_ts is not None and time.time() >= deadline_ts:
            raise DeadlineExceededError(
                f"request deadline expired before {where} — shed, "
                "not executed")

    @staticmethod
    def _deadline_timeout() -> float | None:
        from ant_ray_tpu.serve.api import get_request_deadline  # noqa: PLC0415

        deadline_ts = get_request_deadline()
        if deadline_ts is None:
            return None
        return max(0.0, deadline_ts - time.time())

    @staticmethod
    def _is_chat(request: dict) -> bool:
        path = request.get("__route_path__", "")
        return "messages" in request or path.endswith("/chat/completions")

    def _submit(self, prompt, sampling, session_id=None):
        """Admission (typed shed inside the `llm:admission` span) +
        enqueue to the engine loop."""
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        ctx = tracing_plane.current()
        with tracing_plane.span("llm:admission"):
            return self._loop.submit(prompt, sampling,
                                     session_id=session_id,
                                     trace_ctx=ctx)

    def _wait(self, handle, where: str):
        from ant_ray_tpu.exceptions import DeadlineExceededError  # noqa: PLC0415

        timeout = self._deadline_timeout()
        try:
            return handle.wait(timeout)
        except TimeoutError as exc:
            raise DeadlineExceededError(
                f"request deadline expired during {where}") from exc

    def __call__(self, request: dict) -> dict:
        """OpenAI-shaped request.  Completions: {"prompt": ...} →
        choices[].text.  Chat (/v1/chat/completions or a "messages"
        key): templated through the tokenizer's chat template →
        choices[].message (ref: the OpenAI-compatible serving surface,
        llm/_internal/serve/deployments/llm/llm_server.py).  An
        optional ``session_id`` pins the request to a persistent KV
        session (multi-turn reuse + tiered offload)."""
        if self._is_chat(request):
            return self._chat(request)
        prompts = request.get("prompt", "")
        many = isinstance(prompts, list) and prompts and not isinstance(
            prompts[0], int)
        batch = prompts if many else [prompts]
        sampling = self._sampling(request)
        session_id = request.get("session_id")
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        self._check_deadline("generation")
        with tracing_plane.span(
                "llm:generate",
                {"prompts": len(batch),
                 "max_tokens": sampling.max_tokens}):
            handles = [self._submit(p, sampling, session_id=session_id)
                       for p in batch]
            outs = [self._wait(h, "generation") for h in handles]
        return {
            "object": "text_completion",
            "choices": [
                {"index": i, "text": o.text,
                 "token_ids": o.token_ids,
                 "finish_reason": o.finish_reason}
                for i, o in enumerate(outs)
            ],
        }

    def _chat(self, request: dict) -> dict:
        from ant_ray_tpu.llm.chat import render_chat  # noqa: PLC0415
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        token_ids = render_chat(self.engine.tokenizer,
                                request.get("messages", []))
        sampling = self._sampling(request)
        self._check_deadline("generation")
        with tracing_plane.span(
                "llm:generate",
                {"max_tokens": sampling.max_tokens, "chat": True}):
            handle = self._submit(token_ids, sampling,
                                  session_id=request.get("session_id"))
            out = self._wait(handle, "generation")
        return {
            "object": "chat.completion",
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": out.text},
                "finish_reason": out.finish_reason,
            }],
            "usage": {
                "prompt_tokens": len(out.prompt_token_ids),
                "completion_tokens": len(out.token_ids),
                "total_tokens": (len(out.prompt_token_ids)
                                 + len(out.token_ids)),
            },
        }

    @staticmethod
    def _sampling(request: dict) -> SamplingParams:
        return SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())),
            seed=request.get("seed"),
        )

    def stream(self, request: dict):
        """Token-streaming completion: a generator of OpenAI-chunk-shaped
        dicts, consumed through the object plane as a streaming actor
        call (num_returns="streaming") and exposed over SSE by the HTTP
        proxy (ref: serve streaming responses, serve/_private/replica.py
        streaming path).  Tokens stream as the loop produces them —
        other requests keep decoding in the same engine steps."""
        chat = self._is_chat(request)
        if chat:
            from ant_ray_tpu.llm.chat import render_chat  # noqa: PLC0415

            prompt = render_chat(self.engine.tokenizer,
                                 request.get("messages", []))
        else:
            prompts = request.get("prompt", "")
            prompt = prompts[0] if isinstance(prompts, list) and prompts \
                and not isinstance(prompts[0], int) else prompts
        sampling = self._sampling(request)
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        self._check_deadline("streaming generation")
        with tracing_plane.span(
                "llm:stream",
                {"max_tokens": sampling.max_tokens, "chat": chat}):
            handle = self._submit(prompt, sampling,
                                  session_id=request.get("session_id"))
            yield from (self._chat_chunks(handle) if chat
                        else self._chunks(handle))

    def _events(self, handle):
        """Handle events → the engine-stream delta shape."""
        decode = self.engine.tokenizer.decode
        for ev in handle:
            if ev["type"] == "token":
                tok = ev["token_id"]
                yield {"token_id": tok, "text": decode([tok]),
                       "finished": False, "finish_reason": None}
            elif ev["type"] == "error":
                raise ev["error"]
            else:
                out = ev["output"]
                yield {"token_id": None, "text": "", "finished": True,
                       "finish_reason": out.finish_reason,
                       "token_ids": list(out.token_ids),
                       "full_text": out.text}

    def _chunks(self, handle):
        for delta in self._events(handle):
            if delta["finished"]:
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0, "text": "",
                                    "finish_reason":
                                        delta["finish_reason"]}],
                       "done": True}
            else:
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0, "text": delta["text"],
                                    "token_id": delta["token_id"],
                                    "finish_reason": None}],
                       "done": False}

    def _chat_chunks(self, handle):
        for delta in self._events(handle):
            if delta["finished"]:
                yield {"object": "chat.completion.chunk",
                       "choices": [{"index": 0, "delta": {},
                                    "finish_reason":
                                        delta["finish_reason"]}],
                       "done": True}
            else:
                yield {"object": "chat.completion.chunk",
                       "choices": [{"index": 0,
                                    "delta": {"role": "assistant",
                                              "content": delta["text"]},
                                    "finish_reason": None}],
                       "done": False}

    def end_session(self, session_id: str) -> bool:
        """Drop a session's KV state (slot + offloaded slab).  Routed
        through the engine loop so the teardown runs on the loop thread
        — never concurrently with a step mutating the same slot maps."""
        return self._loop.end_session(session_id)

    def load_signals(self) -> dict:
        """Engine load gauges for signal-targeted autoscaling
        (`AutoscalingConfig.target_signal`): art_llm_tokens_per_s,
        art_llm_queue_depth, art_llm_resident_sessions."""
        return self._loop.stats()

    def health(self):
        return "ok"

    def shutdown(self) -> None:
        """Stop the engine loop thread (replica teardown / tests)."""
        self._loop.shutdown()


def build_llm_deployment(model="tiny", *, name: str = "llm",
                         num_replicas: int = 1, slots: int = 8,
                         max_seq: int | None = None,
                         tokenizer_name: str | None = None,
                         tensor_parallel_size: int = 1,
                         route_prefix: str | None = "/v1",
                         max_ongoing_requests: int | None = None,
                         max_queued_requests: int = 0,
                         request_timeout_s: float | None = None,
                         max_waiting: int | None = None,
                         autoscaling_config=None,
                         prefill_chunk_tokens: int | None = 64,
                         decode_steps_per_chunk: int = 1,
                         kv_idle_evict_s: float | None = None,
                         kv_offload="auto"):
    """Application for ``serve.run`` exposing the engine under the
    OpenAI surface: POST /v1/completions and /v1/chat/completions
    (+ streaming via {"stream": true}).

    The overload knobs compose: ``max_ongoing_requests`` /
    ``max_queued_requests`` bound the replica's request gate,
    ``request_timeout_s`` stamps the default end-to-end deadline, and
    ``max_waiting`` bounds the ENGINE's prompt line once every KV slot
    is busy — all sheds surface as 429/RESOURCE_EXHAUSTED with the
    retry hint derived from the measured chunk-drain rate.

    Serving enables chunked prefill by default
    (``prefill_chunk_tokens=64``); ``kv_idle_evict_s`` turns on
    idle-session offload through ``kv_offload`` ("auto" picks the
    object plane inside a cluster).  ``autoscaling_config`` may target
    the engine's published load signals (see
    `AutoscalingConfig.target_signal`)."""
    from ant_ray_tpu import serve  # noqa: PLC0415

    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        route_prefix=route_prefix,
        max_ongoing_requests=max_ongoing_requests,
        max_queued_requests=max_queued_requests,
        request_timeout_s=request_timeout_s,
        autoscaling_config=autoscaling_config)
    return dep.bind(model, slots=slots, max_seq=max_seq,
                    tokenizer_name=tokenizer_name,
                    tensor_parallel_size=tensor_parallel_size,
                    max_waiting=max_waiting,
                    prefill_chunk_tokens=prefill_chunk_tokens,
                    decode_steps_per_chunk=decode_steps_per_chunk,
                    kv_idle_evict_s=kv_idle_evict_s,
                    kv_offload=kv_offload)
