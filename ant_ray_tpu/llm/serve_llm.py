"""Serve deployment wrapping the LLM engine (capability mirror of the
reference's OpenAI-compatible serving layer, ref: llm/_internal/serve/
deployments/ + serve/llm/).

``build_llm_deployment`` returns a serve Application; each replica owns
one engine and drains it per request batch.  The request/response dicts
follow the OpenAI completions shape (``prompt`` → ``choices[].text``)
so a client of the reference's `ray.serve.llm` finds the same surface.
"""

from __future__ import annotations

from ant_ray_tpu.llm.engine import LLMEngine
from ant_ray_tpu.llm.sampling import SamplingParams


class LLMServer:
    """Replica class: one engine per replica."""

    def __init__(self, model="tiny", *, slots: int = 8,
                 max_seq: int | None = None, tokenizer_name: str | None =
                 None, seed: int = 0):
        from ant_ray_tpu.llm.tokenizer import get_tokenizer  # noqa: PLC0415

        self.engine = LLMEngine(
            model, slots=slots, max_seq=max_seq,
            tokenizer=get_tokenizer(tokenizer_name), seed=seed)

    def __call__(self, request: dict) -> dict:
        """OpenAI-completions-shaped request: {"prompt": str|list,
        "max_tokens", "temperature", "top_k", "top_p", "stop_token_ids"}.
        """
        prompts = request.get("prompt", "")
        many = isinstance(prompts, list) and prompts and not isinstance(
            prompts[0], int)
        batch = prompts if many else [prompts]
        sampling = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())),
            seed=request.get("seed"),
        )
        outs = self.engine.generate(batch, sampling)
        return {
            "object": "text_completion",
            "choices": [
                {"index": i, "text": o.text,
                 "token_ids": o.token_ids,
                 "finish_reason": o.finish_reason}
                for i, o in enumerate(outs)
            ],
        }

    def health(self):
        return "ok"


def build_llm_deployment(model="tiny", *, name: str = "llm",
                         num_replicas: int = 1, slots: int = 8,
                         max_seq: int | None = None,
                         tokenizer_name: str | None = None,
                         route_prefix: str | None = "/v1/completions"):
    """Application for ``serve.run`` exposing the engine."""
    from ant_ray_tpu import serve  # noqa: PLC0415

    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        route_prefix=route_prefix)
    return dep.bind(model, slots=slots, max_seq=max_seq,
                    tokenizer_name=tokenizer_name)
