"""Serve deployment wrapping the LLM engine (capability mirror of the
reference's OpenAI-compatible serving layer, ref: llm/_internal/serve/
deployments/ + serve/llm/).

``build_llm_deployment`` returns a serve Application; each replica owns
one engine and drains it per request batch.  The request/response dicts
follow the OpenAI completions shape (``prompt`` → ``choices[].text``)
so a client of the reference's `ray.serve.llm` finds the same surface.
"""

from __future__ import annotations

from ant_ray_tpu.llm.engine import LLMEngine
from ant_ray_tpu.llm.sampling import SamplingParams


class LLMServer:
    """Replica class: one engine per replica."""

    def __init__(self, model="tiny", *, slots: int = 8,
                 max_seq: int | None = None, tokenizer_name: str | None =
                 None, seed: int = 0, tensor_parallel_size: int = 1,
                 max_waiting: int | None = None):
        import threading  # noqa: PLC0415

        from ant_ray_tpu.llm.tokenizer import get_tokenizer  # noqa: PLC0415

        self.engine = LLMEngine(
            model, slots=slots, max_seq=max_seq,
            tokenizer=get_tokenizer(tokenizer_name), seed=seed,
            tensor_parallel_size=tensor_parallel_size,
            max_waiting=max_waiting)
        # The engine mutates shared slot/cache state; replicas may run
        # requests on overlapping threads (max_concurrency > 1), so all
        # engine access serializes here.  Because of that serialization
        # the LOCK QUEUE is the serving-path prompt line: `max_waiting`
        # bounds it in _acquire_engine (the engine's own add_request
        # gate covers direct engine users).
        self._engine_lock = threading.Lock()
        self._max_waiting = max_waiting
        self._lock_waiters = 0
        self._waiters_lock = threading.Lock()

    def _acquire_engine(self) -> None:
        """Admission at the engine boundary: with the engine busy, at
        most ``max_waiting`` requests may line up for the lock — excess
        sheds a typed :class:`BackPressureError` (429 at the ingress)
        instead of piling up blocked replica threads without bound."""
        from ant_ray_tpu.exceptions import BackPressureError  # noqa: PLC0415

        if self._engine_lock.acquire(blocking=False):
            return
        with self._waiters_lock:
            if (self._max_waiting is not None
                    and self._lock_waiters >= self._max_waiting):
                raise BackPressureError(
                    f"llm engine busy: {self._lock_waiters} requests "
                    f"already waiting (max_waiting={self._max_waiting})",
                    retry_after_s=0.5)
            self._lock_waiters += 1
        try:
            self._engine_lock.acquire()
        finally:
            with self._waiters_lock:
                self._lock_waiters -= 1

    @staticmethod
    def _check_deadline(where: str) -> None:
        """Shed a request whose end-to-end deadline (stamped by the
        serve ingress/handle) already expired — generating tokens
        nobody is waiting for would hold the engine lock for nothing."""
        import time  # noqa: PLC0415

        from ant_ray_tpu.exceptions import DeadlineExceededError  # noqa: PLC0415
        from ant_ray_tpu.serve.api import get_request_deadline  # noqa: PLC0415

        deadline_ts = get_request_deadline()  # wall-clock wire field
        if deadline_ts is not None and time.time() >= deadline_ts:
            raise DeadlineExceededError(
                f"request deadline expired before {where} — shed, "
                "not executed")

    @staticmethod
    def _is_chat(request: dict) -> bool:
        path = request.get("__route_path__", "")
        return "messages" in request or path.endswith("/chat/completions")

    def __call__(self, request: dict) -> dict:
        """OpenAI-shaped request.  Completions: {"prompt": ...} →
        choices[].text.  Chat (/v1/chat/completions or a "messages"
        key): templated through the tokenizer's chat template →
        choices[].message (ref: the OpenAI-compatible serving surface,
        llm/_internal/serve/deployments/llm/llm_server.py)."""
        if self._is_chat(request):
            return self._chat(request)
        prompts = request.get("prompt", "")
        many = isinstance(prompts, list) and prompts and not isinstance(
            prompts[0], int)
        batch = prompts if many else [prompts]
        sampling = self._sampling(request)
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        self._check_deadline("generation")
        with tracing_plane.span("llm:admission"):
            self._acquire_engine()
        try:
            self._check_deadline("generation")  # lock wait can expire it
            with tracing_plane.span(
                    "llm:generate",
                    {"prompts": len(batch),
                     "max_tokens": sampling.max_tokens}):
                outs = self.engine.generate(batch, sampling)
        finally:
            self._engine_lock.release()
        return {
            "object": "text_completion",
            "choices": [
                {"index": i, "text": o.text,
                 "token_ids": o.token_ids,
                 "finish_reason": o.finish_reason}
                for i, o in enumerate(outs)
            ],
        }

    def _chat(self, request: dict) -> dict:
        from ant_ray_tpu.llm.chat import render_chat  # noqa: PLC0415

        token_ids = render_chat(self.engine.tokenizer,
                                request.get("messages", []))
        sampling = self._sampling(request)
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        self._check_deadline("generation")
        with tracing_plane.span("llm:admission"):
            self._acquire_engine()
        try:
            self._check_deadline("generation")  # lock wait can expire it
            with tracing_plane.span(
                    "llm:generate",
                    {"max_tokens": sampling.max_tokens, "chat": True}):
                out = self.engine.generate([token_ids], sampling)[0]
        finally:
            self._engine_lock.release()
        return {
            "object": "chat.completion",
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": out.text},
                "finish_reason": out.finish_reason,
            }],
            "usage": {
                "prompt_tokens": len(out.prompt_token_ids),
                "completion_tokens": len(out.token_ids),
                "total_tokens": (len(out.prompt_token_ids)
                                 + len(out.token_ids)),
            },
        }

    @staticmethod
    def _sampling(request: dict) -> SamplingParams:
        return SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())),
            seed=request.get("seed"),
        )

    def stream(self, request: dict):
        """Token-streaming completion: a generator of OpenAI-chunk-shaped
        dicts, consumed through the object plane as a streaming actor
        call (num_returns="streaming") and exposed over SSE by the HTTP
        proxy (ref: serve streaming responses, serve/_private/replica.py
        streaming path)."""
        chat = self._is_chat(request)
        if chat:
            from ant_ray_tpu.llm.chat import render_chat  # noqa: PLC0415

            prompt = render_chat(self.engine.tokenizer,
                                 request.get("messages", []))
        else:
            prompts = request.get("prompt", "")
            prompt = prompts[0] if isinstance(prompts, list) and prompts \
                and not isinstance(prompts[0], int) else prompts
        sampling = self._sampling(request)
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        self._check_deadline("streaming generation")
        # The lock spans the generator's whole life (tokens must stream
        # while generation runs, and no other request may touch the
        # engine mid-stream); the finally releases it even if the
        # consumer abandons the generator (GeneratorExit).
        with tracing_plane.span("llm:admission"):
            self._acquire_engine()
        try:
            self._check_deadline("streaming generation")  # lock wait
            with tracing_plane.span(
                    "llm:stream",
                    {"max_tokens": sampling.max_tokens, "chat": chat}):
                deltas = self.engine.stream(prompt, sampling)
                yield from (self._chat_chunks(deltas) if chat
                            else self._chunks(deltas))
        finally:
            self._engine_lock.release()

    def _chunks(self, deltas):
        for delta in deltas:
            if delta["finished"]:
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0, "text": "",
                                    "finish_reason":
                                        delta["finish_reason"]}],
                       "done": True}
            else:
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0, "text": delta["text"],
                                    "token_id": delta["token_id"],
                                    "finish_reason": None}],
                       "done": False}

    def _chat_chunks(self, deltas):
        for delta in deltas:
            if delta["finished"]:
                yield {"object": "chat.completion.chunk",
                       "choices": [{"index": 0, "delta": {},
                                    "finish_reason":
                                        delta["finish_reason"]}],
                       "done": True}
            else:
                yield {"object": "chat.completion.chunk",
                       "choices": [{"index": 0,
                                    "delta": {"role": "assistant",
                                              "content": delta["text"]},
                                    "finish_reason": None}],
                       "done": False}

    def health(self):
        return "ok"


def build_llm_deployment(model="tiny", *, name: str = "llm",
                         num_replicas: int = 1, slots: int = 8,
                         max_seq: int | None = None,
                         tokenizer_name: str | None = None,
                         tensor_parallel_size: int = 1,
                         route_prefix: str | None = "/v1",
                         max_ongoing_requests: int | None = None,
                         max_queued_requests: int = 0,
                         request_timeout_s: float | None = None,
                         max_waiting: int | None = None):
    """Application for ``serve.run`` exposing the engine under the
    OpenAI surface: POST /v1/completions and /v1/chat/completions
    (+ streaming via {"stream": true}).

    The overload knobs compose: ``max_ongoing_requests`` /
    ``max_queued_requests`` bound the replica's request gate,
    ``request_timeout_s`` stamps the default end-to-end deadline, and
    ``max_waiting`` bounds the ENGINE's prompt line once every KV slot
    is busy — all sheds surface as 429/RESOURCE_EXHAUSTED."""
    from ant_ray_tpu import serve  # noqa: PLC0415

    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        route_prefix=route_prefix,
        max_ongoing_requests=max_ongoing_requests,
        max_queued_requests=max_queued_requests,
        request_timeout_s=request_timeout_s)
    return dep.bind(model, slots=slots, max_seq=max_seq,
                    tokenizer_name=tokenizer_name,
                    tensor_parallel_size=tensor_parallel_size,
                    max_waiting=max_waiting)
