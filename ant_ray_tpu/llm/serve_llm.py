"""Serve deployment wrapping the LLM engine (capability mirror of the
reference's OpenAI-compatible serving layer, ref: llm/_internal/serve/
deployments/ + serve/llm/).

``build_llm_deployment`` returns a serve Application; each replica owns
one engine and drains it per request batch.  The request/response dicts
follow the OpenAI completions shape (``prompt`` → ``choices[].text``)
so a client of the reference's `ray.serve.llm` finds the same surface.
"""

from __future__ import annotations

from ant_ray_tpu.llm.engine import LLMEngine
from ant_ray_tpu.llm.sampling import SamplingParams


class LLMServer:
    """Replica class: one engine per replica."""

    def __init__(self, model="tiny", *, slots: int = 8,
                 max_seq: int | None = None, tokenizer_name: str | None =
                 None, seed: int = 0):
        import threading  # noqa: PLC0415

        from ant_ray_tpu.llm.tokenizer import get_tokenizer  # noqa: PLC0415

        self.engine = LLMEngine(
            model, slots=slots, max_seq=max_seq,
            tokenizer=get_tokenizer(tokenizer_name), seed=seed)
        # The engine mutates shared slot/cache state; replicas may run
        # requests on overlapping threads (max_concurrency > 1), so all
        # engine access serializes here.
        self._engine_lock = threading.Lock()

    def __call__(self, request: dict) -> dict:
        """OpenAI-completions-shaped request: {"prompt": str|list,
        "max_tokens", "temperature", "top_k", "top_p", "stop_token_ids"}.
        """
        prompts = request.get("prompt", "")
        many = isinstance(prompts, list) and prompts and not isinstance(
            prompts[0], int)
        batch = prompts if many else [prompts]
        sampling = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())),
            seed=request.get("seed"),
        )
        with self._engine_lock:
            outs = self.engine.generate(batch, sampling)
        return {
            "object": "text_completion",
            "choices": [
                {"index": i, "text": o.text,
                 "token_ids": o.token_ids,
                 "finish_reason": o.finish_reason}
                for i, o in enumerate(outs)
            ],
        }

    def stream(self, request: dict):
        """Token-streaming completion: a generator of OpenAI-chunk-shaped
        dicts, consumed through the object plane as a streaming actor
        call (num_returns="streaming") and exposed over SSE by the HTTP
        proxy (ref: serve streaming responses, serve/_private/replica.py
        streaming path)."""
        prompts = request.get("prompt", "")
        prompt = prompts[0] if isinstance(prompts, list) and prompts \
            and not isinstance(prompts[0], int) else prompts
        sampling = SamplingParams(
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            stop_token_ids=tuple(request.get("stop_token_ids", ())),
            seed=request.get("seed"),
        )
        # The lock spans the generator's whole life (tokens must stream
        # while generation runs, and no other request may touch the
        # engine mid-stream); the finally releases it even if the
        # consumer abandons the generator (GeneratorExit).
        self._engine_lock.acquire()
        try:
            yield from self._chunks(self.engine.stream(prompt, sampling))
        finally:
            self._engine_lock.release()

    def _chunks(self, deltas):
        for delta in deltas:
            if delta["finished"]:
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0, "text": "",
                                    "finish_reason":
                                        delta["finish_reason"]}],
                       "done": True}
            else:
                yield {"object": "text_completion.chunk",
                       "choices": [{"index": 0, "text": delta["text"],
                                    "token_id": delta["token_id"],
                                    "finish_reason": None}],
                       "done": False}

    def health(self):
        return "ok"


def build_llm_deployment(model="tiny", *, name: str = "llm",
                         num_replicas: int = 1, slots: int = 8,
                         max_seq: int | None = None,
                         tokenizer_name: str | None = None,
                         route_prefix: str | None = "/v1/completions"):
    """Application for ``serve.run`` exposing the engine."""
    from ant_ray_tpu import serve  # noqa: PLC0415

    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        route_prefix=route_prefix)
    return dep.bind(model, slots=slots, max_seq=max_seq,
                    tokenizer_name=tokenizer_name)
