"""ant_ray_tpu.llm — JAX-native LLM serving and batch inference.

Capability mirror of the reference's ``ray.llm`` (ref: python/ray/llm/
_internal/serve/engines/vllm/, deployments/, batch/stages/
vllm_engine_stage.py), re-designed TPU-first: instead of wrapping an
external CUDA engine, the engine IS the framework's own JAX model with
dense per-slot KV slabs, bucketed prefill, and a continuous-batching
scheduler whose compiled step functions have static shapes.
"""

from ant_ray_tpu.llm.batch import build_llm_processor, build_logprob_processor
from ant_ray_tpu.llm.engine import LLMEngine, RequestOutput
from ant_ray_tpu.llm.sampling import SamplingParams
from ant_ray_tpu.llm.tokenizer import ByteTokenizer, get_tokenizer

__all__ = [
    "ByteTokenizer",
    "LLMEngine",
    "RequestOutput",
    "SamplingParams",
    "build_llm_processor",
    "build_logprob_processor",
    "get_tokenizer",
]
