"""Chat templating for /v1/chat/completions (ref capability: the
reference serves chat through the engine's HF tokenizer chat template,
llm/_internal/serve/deployments/llm/llm_server.py chat path).

``render_chat`` prefers the tokenizer's own ``apply_chat_template``
(HF tokenizers ship the model's template); tokenizers without one (the
dependency-free ByteTokenizer) get a minimal generic template with an
assistant generation prompt.
"""

from __future__ import annotations

ROLE_ORDER = ("system", "user", "assistant", "tool")


def render_chat(tokenizer, messages: list, *,
                add_generation_prompt: bool = True):
    """messages: [{"role": ..., "content": ...}, ...] → token ids."""
    if not messages:
        raise ValueError("empty messages")
    for m in messages:
        if "role" not in m or "content" not in m:
            raise ValueError(f"malformed chat message: {m!r}")
    apply = getattr(tokenizer, "apply_chat_template", None)
    if callable(apply):
        try:
            return list(apply(
                messages, add_generation_prompt=add_generation_prompt,
                tokenize=True))
        except Exception:  # noqa: BLE001 — template-less HF tokenizer
            pass
    text = "".join(
        f"<|{m['role']}|>\n{m['content']}\n" for m in messages)
    if add_generation_prompt:
        text += "<|assistant|>\n"
    return tokenizer.encode(text)
