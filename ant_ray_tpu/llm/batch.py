"""Offline batch inference over Datasets (capability mirror of the
reference's ``ray.data.llm`` batch stages, ref: llm/_internal/batch/
stages/vllm_engine_stage.py).

``build_llm_processor`` returns a Dataset→Dataset callable that routes
every block through actor-held engines, so model weights load once per
actor rather than once per block.
"""

from __future__ import annotations

from ant_ray_tpu.llm.sampling import SamplingParams


def build_llm_processor(model="tiny", *, concurrency: int = 1,
                        slots: int = 8, max_seq: int | None = None,
                        sampling: SamplingParams | None = None,
                        prompt_key: str = "prompt",
                        output_key: str = "generated_text"):
    """rows: dicts with ``prompt_key`` → adds ``output_key``."""
    import ant_ray_tpu as art  # noqa: PLC0415

    sampling = sampling or SamplingParams()

    @art.remote
    class _EngineActor:
        def __init__(self):
            from ant_ray_tpu.llm.engine import LLMEngine  # noqa: PLC0415

            self.engine = LLMEngine(model, slots=slots, max_seq=max_seq)

        def run(self, rows: list) -> list:
            outs = self.engine.generate(
                [r[prompt_key] for r in rows], sampling)
            return [{**row, output_key: out.text}
                    for row, out in zip(rows, outs)]

    actors = [_EngineActor.remote() for _ in range(concurrency)]

    def process(dataset):
        blocks = dataset.materialize()._block_refs
        out_refs = [actors[i % concurrency].run.remote(block)
                    for i, block in enumerate(blocks)]
        from ant_ray_tpu.data.dataset import Dataset  # noqa: PLC0415

        return Dataset(out_refs)

    return process


def build_logprob_processor(model="tiny", *, batch_size: int = 8,
                            prefetch_batches: int = 2,
                            max_len: int | None = None,
                            token_key: str = "tokens",
                            output_key: str = "nll",
                            pad_id: int = 0, sharding=None, seed: int = 0):
    """Batch scoring (per-row mean next-token NLL) over pre-tokenized
    rows, fed through the device-feed iterator
    (``DataIterator.iter_device_batches``): a producer thread pads each
    batch to a fixed ``(batch_size, max_len)`` shape and issues the
    host→device transfer for batch N+1 while the jitted forward for
    batch N runs — the same transfer/compute overlap the Train ingest
    path gets.

    rows: dicts with ``token_key`` → list of token ids.  Returns a
    Dataset→Dataset callable producing rows ``{"row": i, output_key:
    nll_per_token}`` aligned with the input order (the feed's
    ``tail_padded_rows`` stat trims the padded tail).
    """
    import numpy as np  # noqa: PLC0415

    from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415
    from ant_ray_tpu.models import checkpoint as ckpt  # noqa: PLC0415
    from ant_ray_tpu.models import llama  # noqa: PLC0415

    jax = import_jax()
    import jax.numpy as jnp  # noqa: PLC0415
    import optax  # noqa: PLC0415

    loaded, config = ckpt.resolve_model(model)
    params = (loaded if loaded is not None
              else llama.init_params(config, jax.random.PRNGKey(seed)))
    seq = min(max_len or 128, config.max_seq)

    def _nll(params, tokens):
        mask = (tokens != pad_id).astype(jnp.float32)
        logits = llama.forward(params, tokens[:, :-1], config)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens[:, 1:])
        m = mask[:, 1:]
        return (losses * m).sum(-1) / jnp.maximum(m.sum(-1), 1.0)

    nll_jit = jax.jit(_nll)

    def collate(batch):
        """numpy batch → one dense (n, seq) int32 token array (rows
        truncated/padded to seq; list-block dict rows supported)."""
        if isinstance(batch, dict) and token_key in batch:
            col = list(batch[token_key])
        else:
            col = [r[token_key] for r in batch.get("value", [])]
        out = np.full((len(col), seq), pad_id, np.int32)
        for i, ids in enumerate(col):
            ids = list(ids)[:seq]
            out[i, :len(ids)] = ids
        return {"tokens": out}

    def process(dataset):
        it = dataset.iterator()
        nlls = []
        for batch in it.iter_device_batches(
                batch_size, prefetch_batches=prefetch_batches,
                sharding=sharding, collate_fn=collate, pad_value=pad_id):
            nlls.append(np.asarray(nll_jit(params, batch["tokens"])))
        feed = it.stats()["device_feed"]
        n_valid = feed["batches"] * batch_size - feed["tail_padded_rows"]
        flat = (np.concatenate(nlls)[:n_valid] if nlls
                else np.zeros((0,), np.float32))
        from ant_ray_tpu.data.dataset import from_items  # noqa: PLC0415

        return from_items(
            [{"row": i, output_key: float(v)} for i, v in enumerate(flat)])

    return process
