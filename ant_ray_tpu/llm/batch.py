"""Offline batch inference over Datasets (capability mirror of the
reference's ``ray.data.llm`` batch stages, ref: llm/_internal/batch/
stages/vllm_engine_stage.py).

``build_llm_processor`` returns a Dataset→Dataset callable that routes
every block through actor-held engines, so model weights load once per
actor rather than once per block.
"""

from __future__ import annotations

from ant_ray_tpu.llm.sampling import SamplingParams


def build_llm_processor(model="tiny", *, concurrency: int = 1,
                        slots: int = 8, max_seq: int | None = None,
                        sampling: SamplingParams | None = None,
                        prompt_key: str = "prompt",
                        output_key: str = "generated_text"):
    """rows: dicts with ``prompt_key`` → adds ``output_key``."""
    import ant_ray_tpu as art  # noqa: PLC0415

    sampling = sampling or SamplingParams()

    @art.remote
    class _EngineActor:
        def __init__(self):
            from ant_ray_tpu.llm.engine import LLMEngine  # noqa: PLC0415

            self.engine = LLMEngine(model, slots=slots, max_seq=max_seq)

        def run(self, rows: list) -> list:
            outs = self.engine.generate(
                [r[prompt_key] for r in rows], sampling)
            return [{**row, output_key: out.text}
                    for row, out in zip(rows, outs)]

    actors = [_EngineActor.remote() for _ in range(concurrency)]

    def process(dataset):
        blocks = dataset.materialize()._block_refs
        out_refs = [actors[i % concurrency].run.remote(block)
                    for i, block in enumerate(blocks)]
        from ant_ray_tpu.data.dataset import Dataset  # noqa: PLC0415

        return Dataset(out_refs)

    return process
