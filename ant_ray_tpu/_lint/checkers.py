"""The project-specific checkers.  Each rule encodes a bug class this
repo has already shipped once — the ``prevents`` string names it.

Heuristics over proofs: these are AST pattern matchers, not a type
system.  A rule that cries wolf gets suppressed into uselessness, so
every matcher is written to UNDER-match (e.g. ``.call()`` is only a
blocking RPC when the receiver is named like a client) and deliberate
sites carry ``# artlint: disable=<rule> — <why>`` rationale comments.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Iterable

from ant_ray_tpu._lint.framework import (
    Checker,
    Finding,
    ProjectChecker,
)

# ------------------------------------------------------------ shared bits

#: Attribute calls that park the calling thread on I/O or a subprocess.
_SOCKET_BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "recvfrom",
                          "recvmsg"}
#: ``send`` blocks too, but only flag it on receivers that are plainly
#: sockets/collectives — ``generator.send`` is everywhere and harmless.
_SEND_BASES = {"sock", "socket", "conn", "col"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}
#: Receiver names that mark ``.call()`` as a synchronous RPC.
_RPC_BASES = {"gcs", "rpc"}
_LOCKISH_RE = re.compile(r"lock|mutex|cond|_cv$", re.IGNORECASE)


def _terminal_name(node: ast.AST) -> str:
    """The rightmost identifier of a Name/Attribute/Call chain:
    ``self._chunk_cache_lock`` -> ``_chunk_cache_lock``,
    ``_pair_lock(g, s)`` -> ``_pair_lock``."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _base_name(func: ast.Attribute) -> str:
    """The terminal name of an attribute call's receiver:
    ``runtime._clients.get`` -> ``_clients``, ``time.sleep`` -> ``time``."""
    return _terminal_name(func.value)


def _is_lockish(expr: ast.AST) -> bool:
    return bool(_LOCKISH_RE.search(_terminal_name(expr)))


class _StmtTracker(ast.NodeVisitor):
    """NodeVisitor that remembers the innermost enclosing statement.
    Findings anchor there: that is the line a fix edits and the line a
    ``# artlint: disable`` comment block sits above (a directive above
    a multi-line statement must suppress a match on a continuation
    line)."""

    def __init__(self):
        self._stmt: ast.stmt | None = None

    def visit(self, node):
        if isinstance(node, ast.stmt):
            self._stmt = node
        return super().visit(node)

    def anchor(self, node: ast.AST) -> ast.AST:
        return self._stmt if self._stmt is not None else node

    def stmt_header_span(self, node: ast.AST) -> tuple[int, int]:
        """(start, end) lines of the enclosing statement's HEADER: for
        a compound statement (If/While/With/...) the span stops before
        the first body statement — `if time.time() - t > 60:` must not
        be exempted by what its body happens to mention."""
        stmt = self.anchor(node)
        start = stmt.lineno
        body = getattr(stmt, "body", None)
        if isinstance(body, list) and body \
                and isinstance(body[0], ast.stmt):
            return start, max(start, body[0].lineno - 1)
        return start, getattr(stmt, "end_lineno", start) or start


def _blocking_call(node: ast.Call) -> str | None:
    """Why this call blocks the thread, or None.  The deny-list mirrors
    the repo's real blocking surface: time.sleep, socket I/O,
    subprocess, sync RpcClient.call, concurrent.futures ``result()``."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    attr, base = func.attr, _base_name(func)
    base_l = base.lower()
    if attr == "sleep" and base == "time":
        return "time.sleep() parks the thread"
    if attr in _SOCKET_BLOCKING_ATTRS:
        return f"socket .{attr}() blocks on the wire"
    if attr == "send" and base_l in _SEND_BASES:
        return f"{base}.send() blocks on the wire"
    if base == "subprocess" and attr in _SUBPROCESS_FNS:
        return f"subprocess.{attr}() blocks on a child process"
    if attr == "call" and ("client" in base_l or base_l in _RPC_BASES):
        return (f"sync RPC {base}.call() blocks on a round trip "
                "(use call_async / oneway)")
    if attr == "result" and len(node.args) <= 1 and not node.keywords:
        return "future .result() parks the thread on remote completion"
    return None


# -------------------------------------------------------------- checkers

class BlockingUnderLockChecker(Checker):
    """Blocking calls inside ``with <lock>:`` bodies serialize every
    contender behind one I/O round trip — the whole plane stalls, not
    one caller.

    DELIBERATE over-match: nested ``def``s inside the critical section
    are scanned too (unlike blocking-in-async, which exempts them).
    The historical bug this rule encodes lived in exactly such a
    helper — ``_recv_all`` defined under the tensor-transport pair
    lock and executed while it was held.  A callback that is defined
    under a lock but genuinely invoked lock-free carries a rationale
    suppression instead."""

    rule = "blocking-under-lock"
    prevents = ("ADVICE round 5: blocking col.send() under a "
                "module-global lock serialized all tensor transfers")
    scope = ("ant_ray_tpu/_private/", "ant_ray_tpu/experimental/",
             "ant_ray_tpu/util/collective/")

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        checker = self
        findings: list[Finding] = []

        class V(_StmtTracker):
            def __init__(self):
                super().__init__()
                self.lock_depth = 0

            def visit_With(self, node: ast.With):
                held = any(_is_lockish(i.context_expr)
                           for i in node.items)
                self.lock_depth += held
                self.generic_visit(node)
                self.lock_depth -= held

            def visit_Call(self, node: ast.Call):
                if self.lock_depth:
                    why = _blocking_call(node)
                    if why:
                        findings.append(checker.finding(
                            rel_path, self.anchor(node),
                            f"{why} while a lock is held — move the "
                            "blocking work outside the critical "
                            "section (snapshot under the lock, then "
                            "do I/O)", lines))
                self.generic_visit(node)

        V().visit(tree)
        return findings


class BlockingInAsyncChecker(Checker):
    """The same blocking set inside ``async def`` parks the whole event
    loop: every coroutine sharing it stalls, heartbeats included."""

    rule = "blocking-in-async"
    prevents = ("daemon-plane review: one sync RPC on the io loop "
                "freezes every in-flight request on that process")

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        checker = self
        findings: list[Finding] = []

        class V(_StmtTracker):
            def __init__(self):
                super().__init__()
                self.async_depth = 0

            def visit_AsyncFunctionDef(self, node):
                self.async_depth += 1
                self.generic_visit(node)
                self.async_depth -= 1

            def visit_FunctionDef(self, node):
                # A nested sync def runs wherever it is CALLED (often a
                # thread-pool executor) — not on the loop.
                saved, self.async_depth = self.async_depth, 0
                self.generic_visit(node)
                self.async_depth = saved

            visit_Lambda = visit_FunctionDef

            def visit_Call(self, node: ast.Call):
                if self.async_depth:
                    why = _blocking_call(node)
                    if why:
                        findings.append(checker.finding(
                            rel_path, self.anchor(node),
                            f"{why} inside async def — this parks the "
                            "event loop; await the async variant or "
                            "run_in_executor", lines))
                self.generic_visit(node)

        V().visit(tree)
        return findings


class BannedApisChecker(Checker):
    """APIs with a strictly-better project-native replacement.

    * ``asyncio.iscoroutine`` → ``inspect.iscoroutine``: on py<3.12 the
      asyncio variant also matches plain generators, which fed streaming
      tasks' generators to the event loop ("Task got bad yield" — the
      root cause of all 8 pre-PR-5 tier-1 failures).
    * ``time.time()`` in duration/deadline arithmetic →
      ``time.monotonic()``: wall clock steps under NTP correction, so
      intervals computed from it can go negative or jump hours.
      Cross-process wire fields are the sanctioned exception — wall
      clock is the only clock two hosts share.  Statements mentioning
      ``deadline_ts`` (the wire-deadline naming convention) are
      allowlisted automatically; other deliberate sites carry a
      ``# artlint: disable=banned-apis — <why>`` rationale.
    * bare ``asyncio.ensure_future(...)`` (result discarded, or the
      function passed as a callback) in ``_private/`` →
      ``protocol._spawn``: the event loop keeps only a WEAK reference
      to tasks, so a fire-and-forget task with no strong ref can be
      garbage-collected mid-flight and silently never finish (the
      actor-sender restart path would strand a whole actor's queue).
      Holding the returned task (assignment, container, await) is the
      other sanctioned fix and is not flagged.
    """

    rule = "banned-apis"
    prevents = ("PR 5 root cause: asyncio.iscoroutine matched plain "
                "generators on py<3.12 (all 8 pre-existing tier-1 "
                "failures); NTP steps break time.time() intervals; "
                "GC'd fire-and-forget tasks strand actor send queues")

    #: Where the ensure_future rule applies: the always-on control-plane
    #: daemons, where a GC'd background task is a silent outage.
    _SPAWN_SCOPE = ("ant_ray_tpu/_private/",)

    #: Identifiers whose presence on the flagged line marks the value as
    #: a cross-process wire field (wall clock is correct there).
    wallclock_wire_names = ("deadline_ts",)

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        checker = self
        findings: list[Finding] = []

        def _is_time_time(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "time"
                    and _base_name(node.func) == "time")

        def _is_ensure_future(node: ast.AST) -> bool:
            return (isinstance(node, ast.Attribute)
                    and node.attr == "ensure_future"
                    and _base_name(node) == "asyncio")

        spawn_scoped = any(rel_path.startswith(p)
                           for p in self._SPAWN_SCOPE)
        # Attribute nodes serving as a call's callee — a bare
        # `asyncio.ensure_future` reference OUTSIDE this set is being
        # passed around as a callback (the call_soon_threadsafe shape).
        callee_ids = {id(n.func) for n in ast.walk(tree)
                      if isinstance(n, ast.Call)}

        class V(_StmtTracker):
            def _flag_time_arith(self, node: ast.AST):
                # Findings anchor on the enclosing STATEMENT: that is
                # the line a fix edits and the line a disable comment
                # sits above.  The wire-field allowlist scans only the
                # statement HEADER — an `if time.time() - t0 > 60:`
                # must not be exempted because its body happens to
                # mention deadline_ts.
                anchor = self.anchor(node)
                start, end = self.stmt_header_span(node)
                text = " ".join(lines[start - 1:end])
                if any(name in text
                       for name in checker.wallclock_wire_names):
                    return
                findings.append(checker.finding(
                    rel_path, anchor,
                    "time.time() in duration/deadline arithmetic — "
                    "use time.monotonic() (wall clock steps under "
                    "NTP); keep wall clock only for cross-process "
                    "wire fields, with a disable comment saying so",
                    lines))

            def visit_Call(self, node: ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr == "iscoroutine"
                        and _base_name(func) == "asyncio"):
                    findings.append(checker.finding(
                        rel_path, node,
                        "asyncio.iscoroutine() also matches plain "
                        "generators on py<3.12 — use "
                        "inspect.iscoroutine()", lines))
                self.generic_visit(node)

            def visit_Expr(self, node: ast.Expr):
                v = node.value
                if (spawn_scoped and isinstance(v, ast.Call)
                        and _is_ensure_future(v.func)):
                    findings.append(checker.finding(
                        rel_path, node,
                        "bare asyncio.ensure_future() discards its "
                        "task — the loop holds only a weak ref, so it "
                        "can be GC'd mid-flight; use protocol._spawn "
                        "(or hold the returned task)", lines))
                self.generic_visit(node)

            def visit_Attribute(self, node: ast.Attribute):
                if (spawn_scoped and _is_ensure_future(node)
                        and id(node) not in callee_ids):
                    findings.append(checker.finding(
                        rel_path, self.anchor(node),
                        "asyncio.ensure_future passed as a bare "
                        "callback — nothing holds the spawned task, so "
                        "it can be GC'd mid-flight; pass "
                        "protocol._spawn instead", lines))
                self.generic_visit(node)

            def visit_BinOp(self, node: ast.BinOp):
                if isinstance(node.op, (ast.Add, ast.Sub)) and (
                        _is_time_time(node.left)
                        or _is_time_time(node.right)):
                    self._flag_time_arith(node)
                self.generic_visit(node)

            def visit_Compare(self, node: ast.Compare):
                if any(_is_time_time(n)
                       for n in [node.left, *node.comparators]):
                    self._flag_time_arith(node)
                self.generic_visit(node)

        V().visit(tree)
        return findings


class BaseExceptionSwallowChecker(Checker):
    """``except:`` / ``except BaseException`` without a re-raise eats
    the interrupts this codebase treats as control flow:
    ``train.PreemptionInterrupt`` is a BaseException BY DESIGN (so user
    ``except Exception`` can't swallow a node drain) and
    ``asyncio.CancelledError`` drives every shutdown path.

    The error-channeling idiom is exempt: a handler that binds the
    exception (``as e``) and forwards the bound value somewhere a
    consumer will re-raise it (queue.put, set_exception, storing it
    for a reply) propagates rather than swallows.  Merely LOGGING the
    bound name is not channeling — ``logger.warning("ignored: %s", e)``
    is the canonical swallow, the exact PR 6 pattern this rule exists
    to catch.
    """

    rule = "baseexception-swallow"
    prevents = ("PR 6: a broad handler in the unwind path would eat "
                "PreemptionInterrupt and re-run completed train steps")

    #: Callee names whose arguments are considered CONSUMED, not
    #: forwarded: a reference that only feeds these is still a swallow.
    _LOG_CALLEES = frozenset(
        {"debug", "info", "warning", "warn", "error", "exception",
         "critical", "log", "print"})

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
                continue
            if node.name and self._channels(node):
                continue
            what = ("bare except:" if node.type is None
                    else "except BaseException")
            findings.append(self.finding(
                rel_path, node,
                f"{what} without re-raise swallows PreemptionInterrupt/"
                "CancelledError — narrow to Exception, or re-raise "
                "BaseExceptions before handling", lines))
        return findings

    def _channels(self, handler: ast.ExceptHandler) -> bool:
        """True when the bound exception is referenced OUTSIDE logging
        calls — forwarded to a queue/future/variable a consumer will
        re-raise, rather than printed and dropped."""
        logged_refs: set[int] = set()
        all_refs: list[ast.Name] = []
        for stmt in handler.body:
            for n in ast.walk(stmt):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in self._LOG_CALLEES) or (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Name)
                        and n.func.id in self._LOG_CALLEES):
                    for arg in ast.walk(n):
                        if isinstance(arg, ast.Name) \
                                and arg.id == handler.name:
                            logged_refs.add(id(arg))
                elif isinstance(n, ast.Name) and n.id == handler.name:
                    all_refs.append(n)
        return any(id(ref) not in logged_refs for ref in all_refs)

    @staticmethod
    def _is_broad(type_node: ast.AST | None) -> bool:
        if type_node is None:
            return True
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return any(_terminal_name(n) == "BaseException" for n in nodes)


class ResponseTruthinessChecker(Checker):
    """Truth-testing an aiohttp ``web.Response``: an unprepared response
    defines ``__len__`` via its body and is FALSY, so ``resp or
    fallback`` / ``if resp:`` silently drops a typed reply.  Compare
    against ``None`` explicitly."""

    rule = "response-truthiness"
    prevents = ("PR 7 third review round: `resp or fallback` replaced "
                "a typed 429 (empty body => falsy Response) with a 500")
    scope = ("ant_ray_tpu/serve/", "ant_ray_tpu/_private/dashboard.py")

    _RESPONSE_CALL_RE = re.compile(
        r"(Response$)|(^json_response$)|(_response$)")

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(rel_path, node,
                                                     lines))
        return findings

    def _response_names(self, fn: ast.AST) -> set[str]:
        names: set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            callee = _terminal_name(node.value.func)
            if not self._RESPONSE_CALL_RE.search(callee):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    names.add(target.id)
        return names

    def _check_function(self, rel_path: str, fn: ast.AST,
                        lines: list[str]) -> Iterable[Finding]:
        names = self._response_names(fn)
        if not names:
            return []

        def bad(expr: ast.AST) -> bool:
            return isinstance(expr, ast.Name) and expr.id in names

        findings = []

        def flag(expr: ast.AST, how: str):
            findings.append(self.finding(
                rel_path, expr,
                f"truth-testing Response-bound name "
                f"'{expr.id}' ({how}) — an unprepared web.Response "  # type: ignore[attr-defined]
                "with an empty body is FALSY; compare `is None` "
                "instead", lines))

        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)) \
                    and bad(node.test):
                flag(node.test, "if/while test")
            elif isinstance(node, ast.BoolOp):
                for value in node.values:
                    if bad(value):
                        flag(value, "and/or chain")
            elif isinstance(node, ast.UnaryOp) \
                    and isinstance(node.op, ast.Not) and bad(node.operand):
                flag(node.operand, "not <resp>")
            elif isinstance(node, ast.Assert) and bad(node.test):
                flag(node.test, "assert")
        return findings


# ---------------------------------------------------- wire-schema drift

def snapshot_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "wire_methods.json")


def load_snapshot(path: str | None = None) -> dict:
    try:
        with open(path or snapshot_path()) as f:
            return json.load(f).get("methods", {})
    except (OSError, ValueError):
        return {}


def save_snapshot(path: str | None = None) -> None:
    from ant_ray_tpu._private import wire_schema  # noqa: PLC0415

    methods = {name: entry["since"]
               for name, entry in sorted(wire_schema.METHODS.items())}
    with open(path or snapshot_path(), "w") as f:
        json.dump({"comment": "additive-only METHODS snapshot — a "
                              "removed/renamed RPC or a changed `since` "
                              "fails wire-schema-drift; record additions "
                              "with --baseline-update",
                   "methods": methods}, f, indent=1)
        f.write("\n")


class WireSchemaDriftChecker(ProjectChecker):
    """The PR 8 one-off lint generalized: the wire-schema registry, the
    tracing plane table, and the committed snapshot must agree.

    * every METHODS entry well-formed (service/payload/reply non-empty,
      ``since`` <= PROTOCOL_VERSION);
    * METHODS ≡ RPC_METHOD_PLANES, both directions (an RPC cannot ship
      without deciding its latency-aggregation plane);
    * additive-only vs the committed snapshot: a method present in the
      snapshot but gone from METHODS (rename/removal), or whose
      ``since`` changed, fails loudly — mixed-version peers would
      mis-route; genuinely new methods are recorded with
      ``--baseline-update``.
    """

    rule = "wire-schema-drift"
    prevents = ("PR 8's one-off test generalized: an RPC renamed or "
                "shipped without a latency plane breaks mixed-version "
                "peers / ships untraced")

    _SCHEMA_PATH = "ant_ray_tpu/_private/wire_schema.py"

    def __init__(self, methods: dict | None = None,
                 planes: dict | None = None,
                 snapshot: dict | None = None,
                 protocol_version: int | None = None):
        # Injectable for fixture tests; None = the real registries.
        self._methods = methods
        self._planes = planes
        self._snapshot = snapshot
        self._protocol_version = protocol_version

    def _load(self):
        from ant_ray_tpu._private import protocol, wire_schema  # noqa: PLC0415
        from ant_ray_tpu.observability.tracing_plane import (  # noqa: PLC0415
            RPC_METHOD_PLANES)

        methods = (self._methods if self._methods is not None
                   else wire_schema.METHODS)
        planes = (self._planes if self._planes is not None
                  else RPC_METHOD_PLANES)
        snapshot = (self._snapshot if self._snapshot is not None
                    else load_snapshot())
        version = (self._protocol_version
                   if self._protocol_version is not None
                   else protocol.PROTOCOL_VERSION)
        return methods, planes, snapshot, version

    def _line_of(self, package_root: str, method: str) -> int:
        try:
            path = os.path.join(os.path.dirname(package_root),
                                self._SCHEMA_PATH)
            with open(path) as f:
                for i, line in enumerate(f, 1):
                    if f'"{method}"' in line:
                        return i
        except OSError:
            pass
        return 1

    def check_project(self, package_root: str) -> Iterable[Finding]:
        methods, planes, snapshot, version = self._load()
        findings: list[Finding] = []

        def finding(message: str, method: str = "") -> None:
            line = self._line_of(package_root, method) if method else 1
            findings.append(Finding(self.rule, self._SCHEMA_PATH, line,
                                    message, text=method))

        for name, entry in methods.items():
            if not (isinstance(entry, dict) and entry.get("service")
                    and entry.get("payload") and entry.get("reply")
                    and isinstance(entry.get("since"), int)):
                finding(f"METHODS[{name!r}] malformed: needs non-empty "
                        "service/payload/reply and an int `since`", name)
            elif entry["since"] > version:
                finding(f"METHODS[{name!r}].since={entry['since']} is "
                        f"ahead of PROTOCOL_VERSION={version}", name)

        for name in sorted(set(methods) - set(planes)):
            finding(f"{name!r} has no RPC_METHOD_PLANES entry — it "
                    "would ship untraced; decide its latency plane in "
                    "observability/tracing_plane.py", name)
        for name in sorted(set(planes) - set(methods)):
            finding(f"RPC_METHOD_PLANES names {name!r}, absent from "
                    "wire_schema.METHODS — stale table entry", name)
        for name, plane in planes.items():
            if not (isinstance(plane, str) and plane):
                finding(f"RPC_METHOD_PLANES[{name!r}] must be a "
                        "non-empty plane label", name)

        for name, since in sorted(snapshot.items()):
            if name not in methods:
                finding(f"{name!r} is in the committed wire snapshot "
                        "but gone from METHODS — removing/renaming an "
                        "RPC breaks mixed-version peers; bump "
                        "PROTOCOL_VERSION and refresh the snapshot "
                        "with --baseline-update", name)
            elif methods[name].get("since") != since:
                finding(f"{name!r} changed since={since} -> "
                        f"{methods[name].get('since')} — a contract "
                        "change needs a PROTOCOL_VERSION bump and a "
                        "snapshot refresh", name)
        for name in sorted(set(methods) - set(snapshot)):
            finding(f"new RPC {name!r} is not in the committed wire "
                    "snapshot — record it with --baseline-update "
                    "(additive evolution is fine; the snapshot is what "
                    "makes removals loud)", name)
        return findings


# ---------------------------------------------------- frame-schema drift

def frame_snapshot_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "wire_frames.json")


def live_frame_schema() -> tuple[dict, dict]:
    """(frame kind/flag constants, ordered hot field tables) as the
    tree defines them right now."""
    from ant_ray_tpu._private import hotframe, protocol  # noqa: PLC0415

    kinds = {
        "REQ": protocol._REQ, "REP": protocol._REP,
        "ERR": protocol._ERR, "ONEWAY": protocol._ONEWAY,
        "HELLO": protocol._HELLO, "GOODBYE": protocol._GOODBYE,
        "HOT": protocol._HOT,
        "RAW_FLAG": protocol._RAW_FLAG,
        "HOT_FLAG": protocol._HOT_FLAG,
        "HOT_WIRE_VERSION": hotframe.HOT_WIRE_VERSION,
        "HOT_TEMPLATE": hotframe.HOT_TEMPLATE,
        "HOT_CALL": hotframe.HOT_CALL,
        "HOT_ACKS": hotframe.HOT_ACKS,
    }
    tables = {
        "hot_template_fields": list(hotframe.TEMPLATE_FIELDS),
        "hot_call_fields": list(hotframe.CALL_FIELDS),
    }
    return kinds, tables


def load_frame_snapshot(path: str | None = None) -> dict:
    try:
        with open(path or frame_snapshot_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_frame_snapshot(path: str | None = None) -> None:
    kinds, tables = live_frame_schema()
    with open(path or frame_snapshot_path(), "w") as f:
        json.dump({"comment": "frame-kind constants + hot-frame field "
                              "tables — values are FROZEN and the "
                              "field tables append-only (a reorder/"
                              "rename breaks peers that negotiated "
                              "the same hot version); record additive "
                              "growth with --baseline-update",
                   "frame_kinds": kinds, **tables}, f, indent=1)
        f.write("\n")


class FrameSchemaDriftChecker(ProjectChecker):
    """The wire-schema drift idea extended below the method registry to
    the FRAME layer the hot wire introduced: transport kind/flag
    constants and the hot-frame field tables must stay frozen /
    append-only against the committed ``wire_frames.json`` snapshot.

    * a frame-kind or flag value that CHANGES (or disappears) fails —
      peers that negotiated the same PROTOCOL_VERSION / hot version
      would mis-parse each other's frames;
    * the hot template/call field tables are ordered wire layout:
      renaming, removing, or REORDERING an entry fails (struct offsets
      shift under the peer); appending is the one legal evolution,
      recorded with ``--baseline-update`` alongside a
      ``HOT_WIRE_VERSION`` bump when layout-affecting.
    """

    rule = "frame-schema-drift"
    prevents = ("a hot-frame field reordered without a version bump "
                "would mis-decode every call between same-version "
                "peers — the wire-schema snapshot idea applied to the "
                "frame layer")

    _HOTFRAME_PATH = "ant_ray_tpu/_private/hotframe.py"

    def __init__(self, kinds: dict | None = None,
                 tables: dict | None = None,
                 snapshot: dict | None = None):
        # Injectable for fixture tests; None = the real registries.
        self._kinds = kinds
        self._tables = tables
        self._snapshot = snapshot

    def check_project(self, package_root: str) -> Iterable[Finding]:
        if self._kinds is not None:
            kinds, tables = self._kinds, self._tables or {}
        else:
            kinds, tables = live_frame_schema()
        snapshot = (self._snapshot if self._snapshot is not None
                    else load_frame_snapshot())
        findings: list[Finding] = []

        def finding(message: str, text: str = "") -> None:
            findings.append(Finding(self.rule, self._HOTFRAME_PATH, 1,
                                    message, text=text))

        for name, value in (snapshot.get("frame_kinds") or {}).items():
            if name not in kinds:
                finding(f"frame kind/flag {name!r} is in the committed "
                        "frame snapshot but gone from the tree — "
                        "removing a frame constant breaks negotiated "
                        "peers", name)
            elif kinds[name] != value:
                finding(f"frame kind/flag {name!r} changed "
                        f"{value} -> {kinds[name]} — frame constants "
                        "are frozen wire contract; introduce a NEW "
                        "kind instead", name)
        for name in sorted(set(kinds) - set(snapshot.get("frame_kinds")
                                            or {})):
            finding(f"new frame kind/flag {name!r} is not in the "
                    "committed frame snapshot — record it with "
                    "--baseline-update", name)

        for table in ("hot_template_fields", "hot_call_fields"):
            live = tables.get(table)
            pinned = snapshot.get(table)
            if live is None or pinned is None:
                if pinned is not None:
                    finding(f"{table} missing from the tree but pinned "
                            "in the frame snapshot", table)
                continue
            if live[:len(pinned)] != pinned:
                finding(f"{table} is not an append-only extension of "
                        f"the committed snapshot ({pinned} -> {live}) "
                        "— renaming/removing/reordering shifts struct "
                        "offsets under same-version peers; append "
                        "only, and bump HOT_WIRE_VERSION for layout "
                        "changes", table)
            elif len(live) > len(pinned):
                finding(f"{table} grew ({len(pinned)} -> {len(live)} "
                        "fields) — record the addition with "
                        "--baseline-update", table)
        return findings


class PickleInHotPathChecker(Checker):
    """Direct ``pickle.dumps``/``pickle.loads`` on the framing hot path
    outside the blessed helpers.  The zero-pickle frame work holds only
    as long as per-call code keeps using the struct codec — a stray
    pickle call in protocol/hotframe/core wire sections silently
    reintroduces the cost the hot wire removed."""

    rule = "pickle-in-hot-path"
    prevents = ("the PR 15 hot-frame rebuild: pickled TaskSpec frames "
                "cost ~an order of magnitude over the struct codec at "
                "10k calls/s, and a casual pickle.dumps in the framing "
                "layer regresses it invisibly")
    scope = ("ant_ray_tpu/_private/protocol.py",
             "ant_ray_tpu/_private/hotframe.py")

    #: Enclosing functions where pickle IS the job: the generic pickled
    #: framing helpers, and the hot-codec spots that pickle cold/rare
    #: sub-payloads (templates: once per connection; trace contexts:
    #: sampled calls only; exception acks: error path).
    _BLESSED = frozenset({
        "_encode_frame", "_encode_raw_head", "_read_frame",
        "encode_template", "decode_template", "encode_call",
        "decode_call", "encode_ack_exc", "decode_acks",
    })

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        findings: list[Finding] = []
        stack: list[str] = []

        def visit(node: ast.AST) -> None:
            is_fn = isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
            if is_fn:
                stack.append(node.name)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("dumps", "loads") \
                    and _terminal_name(node.func.value) == "pickle" \
                    and not (stack and stack[-1] in self._BLESSED):
                findings.append(self.finding(
                    rel_path, node,
                    f"direct pickle.{node.func.attr}() outside the "
                    "blessed framing helpers "
                    f"({', '.join(sorted(self._BLESSED))}) — per-call "
                    "pickle is what the hot-frame codec exists to "
                    "avoid; route through the codec or a blessed "
                    "helper", lines))
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_fn:
                stack.pop()

        visit(tree)
        return findings


class MetricTagCardinalityChecker(Checker):
    """Per-request identifiers used as metric TAGS.  Every distinct tag
    value mints a new series in the GCS metric store (keyed on
    ``(name, sorted(tags))`` — gcs.py ``_metric_record``), so tagging
    by ``task_id``/``trace_id``/... grows the store linearly with
    traffic until ``MetricsGet`` and the ``/metrics`` scrape drown.
    High-cardinality samples belong in EXEMPLARS (``observe(...,
    exemplar=task_id)`` keeps the last sample per bucket, bounded) —
    the ``exemplar=`` kwarg is deliberately not matched.

    UNDER-match: only literal dict keys in a ``tags={...}`` kwarg on
    metric-shaped calls (``Counter/Gauge/Histogram`` constructors and
    ``.inc/.set/.observe/.record`` methods) and literal ``tag_keys=``
    tuples on the constructors are flagged — a tags dict built in a
    variable is invisible, and that's the accepted price of zero false
    positives."""

    rule = "metric-tag-cardinality"
    prevents = ("observability review: a task_id tag on a latency "
                "histogram minted one series per task and ballooned "
                "the GCS metric store past the /metrics scrape budget")

    _BANNED_KEYS = frozenset({"task_id", "trace_id", "object_id",
                              "request_id"})
    _METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
    _METRIC_METHODS = frozenset({"inc", "set", "observe", "record"})

    def _banned_in(self, node: ast.AST) -> list[str]:
        """Banned identifier strings appearing as literal keys/items."""
        if isinstance(node, ast.Dict):
            items: Iterable[ast.AST] = node.keys
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            items = node.elts
        else:
            return []
        return sorted({n.value for n in items
                       if isinstance(n, ast.Constant)
                       and isinstance(n.value, str)
                       and n.value in self._BANNED_KEYS})

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            is_ctor = name in self._METRIC_CTORS
            is_method = (name in self._METRIC_METHODS
                         and isinstance(node.func, ast.Attribute))
            if not (is_ctor or is_method):
                continue
            for kw in node.keywords:
                if kw.arg == "tags" or (is_ctor and kw.arg == "tag_keys"):
                    banned = self._banned_in(kw.value)
                    if banned:
                        findings.append(self.finding(
                            rel_path, node,
                            f"per-request identifier(s) "
                            f"{', '.join(banned)} as metric tag(s) on "
                            f"{name}() — each distinct value mints a "
                            "new series and grows the GCS metric store "
                            "with traffic; drop the tag or attach the "
                            "id as an exemplar (exemplar= stays "
                            "bounded per bucket)", lines))
        return findings


FILE_CHECKERS: list[Checker] = [
    BlockingUnderLockChecker(),
    BlockingInAsyncChecker(),
    BannedApisChecker(),
    BaseExceptionSwallowChecker(),
    ResponseTruthinessChecker(),
    PickleInHotPathChecker(),
    MetricTagCardinalityChecker(),
]

PROJECT_CHECKERS: list[ProjectChecker] = [
    WireSchemaDriftChecker(),
    FrameSchemaDriftChecker(),
]

ALL_CHECKERS = [*FILE_CHECKERS, *PROJECT_CHECKERS]
