"""``python -m ant_ray_tpu._lint`` — run every checker.

Exit status: 0 when the tree is clean (no new findings AND no stale
baseline entries), 1 otherwise.  ``--baseline-update`` regenerates both
the grandfathered-findings baseline and the additive-only wire-method
snapshot from the current tree, then exits 0.
"""

from __future__ import annotations

import argparse
import sys

from ant_ray_tpu._lint import checkers as _checkers
from ant_ray_tpu._lint.framework import (
    load_baseline,
    run_lint,
    save_baseline,
)


def _list_rules() -> None:
    for checker in _checkers.ALL_CHECKERS:
        scope = getattr(checker, "scope", None)
        where = ", ".join(scope) if scope else "whole package"
        print(f"{checker.rule}\n    scope:    {where}\n"
              f"    prevents: {checker.prevents}")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ant_ray_tpu._lint",
        description="artlint: project-native concurrency & protocol "
                    "static analysis")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the whole "
                             "ant_ray_tpu package + project checkers)")
    parser.add_argument("--baseline-update", action="store_true",
                        help="regenerate baseline.json and "
                             "wire_methods.json from the current tree")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings as fatal")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        _list_rules()
        return 0

    if args.baseline_update:
        if args.paths:
            # A partial pass would overwrite the GLOBAL baseline with
            # one file's findings, silently dropping every other
            # grandfathered entry.
            parser.error("--baseline-update regenerates the global "
                         "baseline; run it without path arguments")
        # Full pass with an empty baseline: everything unsuppressed and
        # not fixed right now is grandfathered (shrink-only from here).
        result = run_lint(None, baseline=[])
        keep = [f for f in result.findings
                if f.rule not in (
                    _checkers.WireSchemaDriftChecker.rule,
                    _checkers.FrameSchemaDriftChecker.rule)]
        save_baseline(keep)
        _checkers.save_snapshot()
        _checkers.save_frame_snapshot()
        print(f"baseline: {len(keep)} grandfathered finding(s); wire "
              f"+ frame snapshots refreshed "
              f"({result.files_checked} files checked)")
        return 0

    baseline = [] if args.no_baseline else load_baseline()
    result = run_lint(args.paths or None, baseline=baseline)

    for finding in result.findings:
        print(finding.render())
    for entry in result.stale_baseline:
        print(f"{entry['path']}: [baseline-stale] grandfathered "
              f"{entry['rule']} finding no longer fires "
              f"({entry['text'][:60]!r}) — shrink the baseline with "
              "--baseline-update")

    if not args.quiet:
        print(f"artlint: {result.files_checked} files, "
              f"{len(result.findings)} finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{result.suppressed} suppressed, "
              f"{len(result.stale_baseline)} stale baseline entr"
              f"{'y' if len(result.stale_baseline) == 1 else 'ies'}",
              file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
