"""Checker framework: findings, suppression comments, the shrink-only
baseline, and the driver that walks a tree and runs every checker.

stdlib-``ast`` only, by design — the linter must run anywhere the
package imports, with zero new dependencies.
"""

from __future__ import annotations

import ast
import json
import os
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Iterable

_SUPPRESS_RE = re.compile(r"#\s*artlint:\s*disable=([\w\-, ]+)")

#: Directories never linted (generated/caches).
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache"}


@dataclass(frozen=True)
class Finding:
    """One violation at ``path:line``.

    ``text`` is the stripped source line — baseline matching keys on
    ``(rule, path, text)`` rather than the line number, so grandfathered
    entries survive unrelated edits shifting lines above them.
    """

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    text: str = ""

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def baseline_key(self) -> tuple:
        return (self.rule, self.path, self.text)

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "text": self.text}

    def render(self) -> str:
        return f"{self.location}: [{self.rule}] {self.message}"


class Checker:
    """Base for per-file AST checkers.

    Subclasses set ``rule`` (the suppression/baseline id), ``prevents``
    (one line naming the historical bug the rule encodes — surfaced by
    ``--list-rules`` and the README table), and optionally ``scope``
    (package-relative path prefixes; None = every file).  Implement
    :meth:`check` yielding Findings; suppression and baseline filtering
    happen in the driver.
    """

    rule: str = ""
    prevents: str = ""
    scope: tuple[str, ...] | None = None

    def applies_to(self, rel_path: str) -> bool:
        if self.scope is None:
            return True
        return any(rel_path.startswith(p) or rel_path == p.rstrip("/")
                   for p in self.scope)

    def check(self, rel_path: str, tree: ast.AST,
              lines: list[str]) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, rel_path: str, node: ast.AST, message: str,
                lines: list[str]) -> Finding:
        line = getattr(node, "lineno", 1)
        text = lines[line - 1].strip() if 0 < line <= len(lines) else ""
        return Finding(self.rule, rel_path, line, message, text)


class ProjectChecker:
    """Base for whole-project checkers (cross-file invariants like the
    wire-schema registry).  Run once per lint pass, only when the pass
    targets the whole package (explicit file arguments skip them)."""

    rule: str = ""
    prevents: str = ""

    def check_project(self, package_root: str) -> Iterable[Finding]:
        raise NotImplementedError


# ------------------------------------------------------------ suppression

def suppressed_rules(lines: list[str], line: int) -> set[str]:
    """Rules disabled at ``line`` (1-based): a directive on the line
    itself, or anywhere in the contiguous block of standalone comment
    lines directly above it (rationales are encouraged to run long)."""
    rules: set[str] = set()

    def collect(idx: int) -> None:
        m = _SUPPRESS_RE.search(lines[idx])
        if m:
            rules.update(r.strip() for r in m.group(1).split(",")
                         if r.strip())

    if 0 <= line - 1 < len(lines):
        collect(line - 1)
    idx = line - 2
    while 0 <= idx < len(lines) and lines[idx].lstrip().startswith("#"):
        collect(idx)
        idx -= 1
    return rules


def is_suppressed(finding: Finding, lines: list[str]) -> bool:
    rules = suppressed_rules(lines, finding.line)
    return finding.rule in rules or "all" in rules


# --------------------------------------------------------------- baseline

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str | None = None) -> list[dict]:
    path = path or default_baseline_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    return data.get("findings", []) if isinstance(data, dict) else data


def save_baseline(findings: list[Finding], path: str | None = None) -> None:
    path = path or default_baseline_path()
    entries = sorted((f.to_json() for f in findings),
                     key=lambda e: (e["rule"], e["path"], e["line"]))
    with open(path, "w") as f:
        json.dump({"comment": "artlint grandfathered findings — may only "
                              "shrink; regenerate with --baseline-update",
                   "findings": entries}, f, indent=1)
        f.write("\n")


def _baseline_counter(entries: list[dict]) -> Counter:
    return Counter((e.get("rule", ""), e.get("path", ""),
                    e.get("text", "")) for e in entries)


# ----------------------------------------------------------------- driver

@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)      # new, fatal
    baselined: list[Finding] = field(default_factory=list)     # grandfathered
    stale_baseline: list[dict] = field(default_factory=list)   # must prune
    suppressed: int = 0
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline


def iter_py_files(target: str) -> Iterable[str]:
    if os.path.isfile(target):
        yield target
        return
    for root, dirs, files in os.walk(target):
        dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def package_root() -> str:
    """The ``ant_ray_tpu`` package directory this module lives in."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rel(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path),
                          os.path.dirname(os.path.abspath(root)))
    if rel.startswith(".."):     # outside the repo: keep the real path
        return os.path.abspath(path).replace(os.sep, "/")
    return rel.replace(os.sep, "/")


def run_lint(targets: list[str] | None = None,
             checkers: list | None = None,
             baseline: list[dict] | None = None,
             with_project_checkers: bool | None = None) -> LintResult:
    """Run ``checkers`` over ``targets`` (default: the whole package).

    Returns a :class:`LintResult`: ``findings`` are NEW violations
    (post-suppression, post-baseline) — a non-empty list fails CI;
    ``stale_baseline`` entries no longer match any finding and must be
    pruned with ``--baseline-update`` (the shrink-only contract).
    """
    from ant_ray_tpu._lint.checkers import (  # noqa: PLC0415 — cycle
        FILE_CHECKERS, PROJECT_CHECKERS)

    root = package_root()
    explicit_targets = targets is not None
    targets = targets or [root]
    if checkers is None:
        checkers = list(FILE_CHECKERS)
        project_checkers = list(PROJECT_CHECKERS)
    else:
        project_checkers = [c for c in checkers
                            if isinstance(c, ProjectChecker)]
        checkers = [c for c in checkers if isinstance(c, Checker)]
    if with_project_checkers is None:
        with_project_checkers = not explicit_targets
    if baseline is None:
        baseline = load_baseline()

    result = LintResult()
    raw: list[tuple[Finding, list[str]]] = []
    for target in targets:
        for path in iter_py_files(target):
            rel = _rel(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError) as e:
                result.findings.append(Finding(
                    "parse-error", rel, getattr(e, "lineno", 1) or 1,
                    f"cannot lint: {e}"))
                continue
            lines = source.splitlines()
            result.files_checked += 1
            for checker in checkers:
                if not checker.applies_to(rel):
                    continue
                for finding in checker.check(rel, tree, lines):
                    raw.append((finding, lines))

    if with_project_checkers:
        for checker in project_checkers:
            for finding in checker.check_project(root):
                raw.append((finding, []))

    remaining = _baseline_counter(baseline)
    for finding, lines in raw:
        if lines and is_suppressed(finding, lines):
            result.suppressed += 1
            continue
        key = finding.baseline_key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            result.baselined.append(finding)
            continue
        result.findings.append(finding)

    for (rule, path, text), count in remaining.items():
        if count > 0:
            result.stale_baseline.append(
                {"rule": rule, "path": path, "text": text, "count": count})
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
