"""artlint — project-native static analysis for the concurrency and
protocol invariants this codebase has already been burned by.

Every rule here encodes a bug class that actually shipped and was found
late, by review or by a failing cluster, instead of mechanically at
commit time:

* ``banned-apis``          — ``asyncio.iscoroutine`` matched plain
  generators on py<3.12 (root cause of all 8 pre-PR-5 tier-1 failures);
  ``time.time()`` in duration arithmetic jumps with NTP steps.
* ``blocking-under-lock``  — a blocking ``col.send()`` under a
  module-global lock serialized every transfer (ADVICE round 5).
* ``blocking-in-async``    — the same blocking set parks the whole
  event loop, not one request.
* ``baseexception-swallow``— broad handlers eat ``PreemptionInterrupt``
  (a BaseException BY DESIGN so user ``except Exception`` can't swallow
  a drain) and ``asyncio.CancelledError`` (PR 6).
* ``response-truthiness``  — an unprepared aiohttp ``web.Response`` has
  ``__len__`` and is FALSY, so ``resp or fallback`` silently replaced a
  typed 429 with a 500 (PR 7, third review round).
* ``wire-schema-drift``    — the PR 8 one-off lint generalized: METHODS
  ≡ RPC_METHOD_PLANES, every entry well-formed, and an additive-only
  snapshot so a renamed/removed RPC fails loudly instead of silently
  breaking mixed-version peers.

Usage::

    python -m ant_ray_tpu._lint                 # lint the package
    python -m ant_ray_tpu._lint path/to/file.py # explicit files
    python -m ant_ray_tpu._lint --baseline-update

Suppression: ``# artlint: disable=<rule>[,<rule>...] — <why>`` on the
flagged line or the line directly above.  The rationale text is part of
the convention: an allowlisted site must say why it is exempt.

Baseline: ``_lint/baseline.json`` grandfathers pre-existing findings so
the linter can land before the debt is zero.  The baseline may only
shrink — stale entries fail the run until ``--baseline-update`` prunes
them, and tests/test_lint.py keeps the whole suite wired into tier-1.

The runtime sibling — the lock-order / long-hold detector — lives in
:mod:`ant_ray_tpu._lint.lockcheck` (opt-in via ``ART_LOCKCHECK=1``).
"""

from ant_ray_tpu._lint.framework import (  # noqa: F401
    Checker,
    Finding,
    LintResult,
    ProjectChecker,
    load_baseline,
    run_lint,
    save_baseline,
)
from ant_ray_tpu._lint.checkers import (  # noqa: F401
    ALL_CHECKERS,
    FILE_CHECKERS,
    PROJECT_CHECKERS,
)
