"""Runtime lock-order detector for the daemon planes (the dynamic
sibling of the static ``blocking-under-lock`` rule).

The static pass proves a blocking call sits inside ONE critical
section; what it cannot see is the ORDER two threads take two locks in.
A→B in the lease path and B→A in the eviction path is a deadlock that
fires once a year, under load, on a Friday.  This module finds it in
any chaos soak instead:

* :func:`make_lock` / :func:`make_rlock` are the factories the daemon
  planes use.  **Off** (the default), they return a plain
  ``threading.Lock`` / ``RLock`` — zero wrappers, zero overhead, the
  exact objects the code used before.  **On** (``ART_LOCKCHECK=1`` or
  ``_system_config={"lockcheck": True}``), they return an instrumented
  wrapper that records, per process:

  - the **lock-acquisition graph**: an edge A→B each time a thread
    acquires B while holding A.  A cycle in that graph is a lock-order
    inversion — two threads interleaving those paths can deadlock —
    and is reported the moment the closing edge is recorded, with both
    edges' acquire stacks.
  - **long holds over blocking calls**: sites the static rule
    allowlisted on purpose (build locks, collective pair locks) call
    :func:`note_blocking`; if a lock held across such a call exceeds
    ``lockcheck_hold_budget_s``, the hold is reported with its acquire
    stack — the evidence review always wanted for "how long is that
    lock actually held?".

* Reports go through the PR 8 flight recorder as force-sampled error
  spans (``lockcheck:cycle`` / ``lockcheck:long-hold``), so a detection
  inside a chaos soak is visible in ``GET /api/flightrecorder`` and the
  GCS span ring like any other failure evidence, plus a logger.error
  for the console.

The detector is a debugging instrument, not a verifier: it observes
orders that actually executed, so coverage is exactly what the soak
exercised.  That is the point — wire it into every chaos run
(tests/test_resilience.py does) and the soaks double as deadlock hunts.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback

logger = logging.getLogger(__name__)

_tls = threading.local()

# Module state (per process).  _STATE_LOCK is a plain lock guarding the
# graph — the detector must not instrument itself.
_STATE_LOCK = threading.Lock()
_edges: dict[str, set[str]] = {}            # name -> names acquired under it
_edge_stacks: dict[tuple[str, str], str] = {}
_reported_cycles: set[frozenset] = set()
_reports: list[dict] = []
_counter = 0

_enabled_cache: bool | None = None


def enabled() -> bool:
    """Lockcheck verdict for this process, decided once: the
    ``ART_LOCKCHECK`` env var (the channel spawned daemons inherit) or
    the ``lockcheck`` config flag (``_system_config`` path)."""
    global _enabled_cache
    if _enabled_cache is None:
        if os.environ.get("ART_LOCKCHECK", "").lower() in ("1", "true",
                                                           "yes"):
            _enabled_cache = True
        else:
            try:
                from ant_ray_tpu._private.config import global_config  # noqa: PLC0415

                _enabled_cache = bool(global_config().lockcheck)
            except Exception:  # noqa: BLE001 — config must never wedge a lock
                _enabled_cache = False
    return _enabled_cache


def refresh_enabled() -> bool:
    """Re-evaluate the verdict.  ``art.init`` calls this after applying
    ``_system_config``: import-time factory calls (the worker singleton)
    may have cached a pre-init False, which would otherwise make the
    config channel dead in the driver process.  Locks created BEFORE
    the refresh stay plain — instrumentation covers everything built
    from init onward (daemons decide once at boot, via the env var
    init exports)."""
    global _enabled_cache
    _enabled_cache = None
    return enabled()


def _hold_budget_s() -> float:
    try:
        from ant_ray_tpu._private.config import global_config  # noqa: PLC0415

        return float(global_config().lockcheck_hold_budget_s)
    except Exception:  # noqa: BLE001
        return 0.25


def make_lock(name: str | None = None):
    """A mutex for the daemon planes.  Disabled: exactly
    ``threading.Lock()``.  Enabled: an :class:`InstrumentedLock`."""
    if not enabled():
        return threading.Lock()
    return InstrumentedLock(threading.Lock(), _name(name))


def make_rlock(name: str | None = None):
    if not enabled():
        return threading.RLock()
    return InstrumentedLock(threading.RLock(), _name(name), reentrant=True)


def _name(name: str | None) -> tuple[str, str]:
    """(display name, graph node id).  The graph is keyed by INSTANCE
    (``name#seq``), not by name: two same-named locks (every ClientPool
    shares "rpc.client_pool") taken A→B on one thread and B→A on
    another are a genuine inversion that name-keying would hide, and a
    cycle stitched together from edges of two *different* instances
    would be a false positive.  Reports render the names."""
    global _counter
    with _STATE_LOCK:
        _counter += 1
        n = _counter
    name = name or f"anon-lock-{n}"
    return name, f"{name}#{n}"


class _Held:
    """One live acquisition on a thread's hold stack."""

    __slots__ = ("node", "t0", "blocking")

    def __init__(self, node: str):
        self.node = node
        self.t0 = time.monotonic()
        self.blocking: str | None = None


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def note_blocking(what: str) -> None:
    """Mark every lock the calling thread currently holds as having
    executed a known-blocking call (sync RPC round trip, socket I/O,
    subprocess).  Free when lockcheck is off; the long-hold report
    fires only for holds that both carried a blocking call AND
    exceeded the budget."""
    if _enabled_cache is not True:   # fast path: disabled or undecided
        if not enabled():
            return
    for held in getattr(_tls, "held", ()) or ():
        if held.blocking is None:
            held.blocking = what


class InstrumentedLock:
    """Context-manager/lock-API wrapper recording acquisition order.

    Not handed to ``threading.Condition`` — conditions manage their own
    lock internals; the daemon planes only wrap plain mutexes."""

    __slots__ = ("_lock", "name", "_node", "_reentrant")

    def __init__(self, lock, name: tuple[str, str],
                 reentrant: bool = False):
        self._lock = lock
        self.name, self._node = name
        self._reentrant = reentrant

    # -- lock API -------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._on_acquire()
        return got

    def release(self):
        self._on_release()
        self._lock.release()

    def locked(self):
        return self._lock.locked() if hasattr(self._lock, "locked") \
            else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- graph bookkeeping ---------------------------------------------
    def _on_acquire(self) -> None:
        stack = _held_stack()
        holding = [h.node for h in stack if h.node != self._node]
        stack.append(_Held(self._node))
        if not holding:
            return
        new_edges = []
        with _STATE_LOCK:
            for outer in holding:
                under = _edges.setdefault(outer, set())
                if self._node not in under:
                    under.add(self._node)
                    new_edges.append(outer)
                    _edge_stacks[(outer, self._node)] = "".join(
                        traceback.format_stack(limit=8)[:-1])
            cycles = [self._find_cycle(outer) for outer in new_edges]
        for cycle in cycles:
            if cycle:
                self._report_cycle(cycle)

    def _find_cycle(self, outer: str) -> list[str] | None:
        """A node path self._node → ... → outer closes the new edge
        outer → self._node into a cycle.  Called under _STATE_LOCK."""
        target, start = outer, self._node
        seen = {start}
        path = [start]

        def dfs(node: str) -> bool:
            if node == target:
                return True
            for nxt in _edges.get(node, ()):
                if nxt in seen:
                    continue
                seen.add(nxt)
                path.append(nxt)
                if dfs(nxt):
                    return True
                path.pop()
            return False

        if dfs(start):
            # path runs start..target; target == outer already heads
            # the cycle, so drop it from the tail: [B, A] renders as
            # B -> A -> B with edges (B->A new, A->B recorded).
            return [outer, *path[:-1]]
        return None

    def _report_cycle(self, nodes: list[str]) -> None:
        key = frozenset(nodes)
        with _STATE_LOCK:
            if key in _reported_cycles:
                return
            _reported_cycles.add(key)
            stacks = {
                f"{a}->{b}": _edge_stacks.get((a, b), "")
                for a, b in zip(nodes, nodes[1:] + nodes[:1])
                if (a, b) in _edge_stacks}
        names = [n.rsplit("#", 1)[0] for n in nodes]
        order = " -> ".join([*names, names[0]])
        report = {"kind": "cycle", "cycle": names, "nodes": list(nodes),
                  "order": order, "stacks": stacks,
                  "thread": threading.current_thread().name}
        _emit(report,
              f"lock-order inversion (potential deadlock): {order}")

    def _on_release(self) -> None:
        stack = getattr(_tls, "held", None)
        if not stack:
            return
        # Non-LIFO release is legal; drop the newest matching entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].node == self._node:
                held = stack.pop(i)
                break
        else:
            return
        if self._reentrant and any(h.node == self._node for h in stack):
            return   # inner release of a reentrant hold
        dur = time.monotonic() - held.t0
        if held.blocking is not None and dur > _hold_budget_s():
            report = {"kind": "long-hold", "lock": self.name,
                      "held_s": round(dur, 4),
                      "blocking": held.blocking,
                      "budget_s": _hold_budget_s(),
                      "thread": threading.current_thread().name}
            _emit(report,
                  f"lock {self.name!r} held {dur:.3f}s across blocking "
                  f"call {held.blocking!r} "
                  f"(budget {_hold_budget_s():.3f}s)")

    def __repr__(self):  # pragma: no cover — debugging aid
        return f"InstrumentedLock({self.name!r})"


def _emit(report: dict, message: str) -> None:
    """Console + flight recorder: the report rides the force-sampled
    ring, so ``/api/flightrecorder`` and the GCS span ring surface it
    even at trace_sample_rate=0."""
    with _STATE_LOCK:
        _reports.append(report)
    logger.error("LOCKCHECK: %s", message)
    try:
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        attrs = {k: (v if isinstance(v, (str, int, float)) else repr(v))
                 for k, v in report.items() if k != "stacks"}
        tracing_plane.record_span(
            tracing_plane.mint(sampled=False),
            f"lockcheck:{report['kind']}", ts=time.time(), dur_s=0.0,
            attrs=attrs, error=True, service="lockcheck")
    except Exception:  # noqa: BLE001 — reporting must never deadlock
        pass


# ----------------------------------------------------------- introspection

def reports() -> list[dict]:
    """Detections so far in this process (tests and soak assertions)."""
    with _STATE_LOCK:
        return list(_reports)


def edges() -> dict[str, set[str]]:
    with _STATE_LOCK:
        return {k: set(v) for k, v in _edges.items()}


def reset(enabled_override: bool | None = None) -> None:
    """Clear graph/report state (tests).  ``enabled_override`` pins the
    verdict without consulting env/config; None re-evaluates lazily."""
    global _enabled_cache
    with _STATE_LOCK:
        _edges.clear()
        _edge_stacks.clear()
        _reported_cycles.clear()
        _reports.clear()
    _enabled_cache = enabled_override
