"""Step-level TPU telemetry plane.

What task-level observability (timeline / tracing / insight) cannot
see is the structure *inside* a training step — the split that actually
determines TPU throughput: how long each step waited on data, on
host→HBM transfer, on compute, on collectives, and how much HBM it
held while doing so.  T3 (arXiv:2401.16677) motivates exactly this
fine-grained compute/collective attribution; the 100k+-GPU collective
paper (arXiv:2510.20171) shows cross-rank skew telemetry is what makes
pod-scale debugging tractable.  This package is that measurement
substrate:

* :class:`StepProfiler` (``step_profiler.py``) — per-step phase
  timings (data_wait / h2d / compute / collective), optional MFU
  against the detected TPU peak, absorbing the device-feed and
  collective-fusion stats streams as phases instead of parallel
  idioms.  Near-zero overhead (< 2 µs/step, benchmarked) and a cheap
  no-op outside a cluster — safe to leave in production loops.
* ``device_stats.py`` — per-device HBM occupancy from
  ``jax.Device.memory_stats()`` (graceful ``None`` on CPU), published
  through the node agent and the GCS metrics table.
* on-demand XLA trace capture — ``POST /api/profile`` on the dashboard
  → node-agent RPC → ``jax.profiler.trace`` into the session dir,
  archive served by the existing log routes.
* Train integration — ``session.report()`` auto-attaches the latest
  step record; the controller aggregates across ranks into Prometheus
  gauges (step-time mean/p50/max, phase fractions, straggler ratio)
  and ``util/timeline.py`` merges step-phase slices as per-rank device
  rows into the chrome trace.
* :mod:`tracing_plane` (``tracing_plane.py``) — the request-level
  plane: W3C-traceparent-shaped contexts minted at every ingress and
  propagated through request metadata, per-process flight recorders
  (force-sampled error rings), the GCS span ring behind
  ``GET /api/trace/{id}``, and ``art_rpc_latency_s`` histograms with
  trace-id exemplars.
"""

from ant_ray_tpu.observability import tracing_plane
from ant_ray_tpu.observability.device_stats import (
    device_memory_stats,
    device_stats_gauges,
)
from ant_ray_tpu.observability.step_profiler import StepProfiler, StepRecord
from ant_ray_tpu.observability.tracing_plane import (
    FlightRecorder,
    TraceContext,
)

__all__ = [
    "FlightRecorder",
    "StepProfiler",
    "StepRecord",
    "TraceContext",
    "device_memory_stats",
    "device_stats_gauges",
    "tracing_plane",
]
