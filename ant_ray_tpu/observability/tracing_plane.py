"""Request-level distributed tracing plane.

What the post-hoc task-event derivation in ``util/tracing.py`` cannot
see is a REQUEST: a serve call that fans out through a handle → replica
→ nested actor tasks → object pulls crosses four processes and none of
the driver-local task events link them.  This module is the Dapper-style
answer built native to our wire protocol:

* a W3C-traceparent-shaped :class:`TraceContext` (``trace_id``,
  ``span_id``, ``sampled``) is MINTED at every ingress — a serve
  HTTP/gRPC request, ``handle.call``, a driver ``.remote()`` — and
  PROPAGATED through request metadata (``TaskSpec.trace_ctx``, serve
  request meta, ``EnsureLocal``/``LeaseWorker`` payload ``trace`` keys)
  so every downstream hop records a child span;
* spans land in a per-process **flight recorder**: two bounded
  GIL-atomic rings (``collections.deque`` appends — no lock on the hot
  path), one for head-sampled spans and a separate one for force-sampled
  error/shed spans so a wrapping ring can never evict the evidence of a
  failure;
* sampled spans batch-publish best-effort to the GCS ``SpanEventsAdd``
  ring (the step-events idiom: oneway, dropped outside a cluster), where
  ``GET /api/trace/{trace_id}``, the Perfetto timeline and the OTLP
  exporters read them back;
* sampled RPCs additionally observe ``art_rpc_latency_s{method,stage}``
  histograms whose exemplars carry the trace id (OpenMetrics practice:
  the histogram names the slow bucket, the exemplar names a trace that
  landed in it).

Cost model (enforced by ``benchmarks/microbench.py`` at
``trace_overhead_unsampled_ns`` < 2 µs): the unsampled path is one
contextvar read, one coin flip amortized into the mint, and — when a
span block is entered at all — two ``perf_counter`` reads and a small
``__slots__`` object, with nothing recorded.  Head sampling is decided
once at mint (``trace_sample_rate``); the sampled flag rides the context
so every downstream hop agrees without re-flipping.
"""

from __future__ import annotations

import atexit
import contextvars
import os
import random
import threading
import time
from collections import deque

from ant_ray_tpu._private.config import global_config

_PID = os.getpid()
_NODE_ID = os.environ.get("ART_NODE_ID", "")[:12]


def set_node_id(node_id_hex: str) -> None:
    """Fix this process's node identity on recorded spans.  Workers get
    it from the ART_NODE_ID env; the node daemon (which mints the ids)
    calls this at registration."""
    global _NODE_ID
    _NODE_ID = (node_id_hex or "")[:12]

_FLUSH_AGE_S = 1.0


class TraceContext:
    """W3C-traceparent-shaped identity of one request: 32-hex trace id,
    16-hex span id of the CURRENT span, and the head-sampling verdict.
    Immutable; ``child()`` mints a fresh span id under the same trace.
    Picklable so contexts survive handles/specs crossing processes."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id,
                            f"{random.getrandbits(64):016x}",
                            self.sampled)

    def to_wire(self) -> tuple:
        return (self.trace_id, self.span_id, self.sampled)

    @classmethod
    def from_wire(cls, wire) -> "TraceContext | None":
        if not wire:
            return None
        return cls(wire[0], wire[1], bool(wire[2]))

    def __reduce__(self):
        return (TraceContext, (self.trace_id, self.span_id, self.sampled))

    def __repr__(self):  # pragma: no cover — debugging aid
        return (f"TraceContext({self.trace_id[:8]}…/{self.span_id}, "
                f"sampled={self.sampled})")


_current: "contextvars.ContextVar[TraceContext | None]" = \
    contextvars.ContextVar("art_trace_ctx", default=None)


def current() -> TraceContext | None:
    """The active trace context in this thread/task, or None."""
    return _current.get()


def current_sampled() -> TraceContext | None:
    """Fast-path accessor: the active context only when sampled (the
    one contextvar read the RPC hot path pays)."""
    ctx = _current.get()
    if ctx is not None and ctx.sampled:
        return ctx
    return None


def mint(sampled: bool | None = None) -> TraceContext:
    """Mint a ROOT context at an ingress.  Head sampling: one coin flip
    against ``trace_sample_rate``; ids are generated even for unsampled
    contexts so a force-sampled error span downstream still has a trace
    identity to hang off.  Request-scale ingresses (serve) use this;
    the per-task hot path uses :func:`maybe_mint`."""
    if sampled is None:
        rate = global_config().trace_sample_rate
        sampled = rate > 0 and random.random() < rate
    return TraceContext(f"{random.getrandbits(128):032x}",
                        f"{random.getrandbits(64):016x}", sampled)


def maybe_mint() -> TraceContext | None:
    """Hot-path ingress mint (driver ``.remote()``): flip the
    head-sampling coin FIRST and generate ids only on a hit — the
    unsampled common case costs one RNG draw and allocates nothing."""
    rate = global_config().trace_sample_rate
    if rate <= 0.0 or random.random() >= rate:
        return None
    return mint(sampled=True)


def set_current(ctx: TraceContext | None):
    return _current.set(ctx)


def reset(token) -> None:
    _current.reset(token)


class use:
    """``with tracing_plane.use(ctx):`` — scope a context (reentrant:
    each instance owns its token)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: TraceContext | None):
        self._ctx = ctx

    def __enter__(self):
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


# ------------------------------------------------------- flight recorder

class FlightRecorder:
    """Per-process bounded span store, always on.

    Two rings: head-sampled spans wrap freely; force-sampled spans
    (errors, sheds) live in their own ring so a burst of healthy
    traffic can never push the evidence of a failure out of memory.
    ``deque.append`` is GIL-atomic — the record path takes no lock."""

    def __init__(self, size: int | None = None):
        if size is None:
            size = max(64, int(global_config().flight_recorder_size))
        self.size = size
        self._ring: deque = deque(maxlen=size)
        self._forced: deque = deque(maxlen=max(64, size // 4))
        # publish batch (sampled spans only), flushed size/age-triggered
        self._pending: list = []
        self._pending_lock = threading.Lock()
        self._last_flush = time.monotonic()
        self._flusher_started = False

    def record(self, span: dict, *, forced: bool = False,
               publish: bool = True) -> None:
        (self._forced if forced else self._ring).append(span)
        if not publish:
            return
        flush_now = False
        with self._pending_lock:
            self._pending.append(span)
            now = time.monotonic()
            if (len(self._pending)
                    >= global_config().trace_publish_batch
                    or now - self._last_flush > _FLUSH_AGE_S):
                flush_now = True
            if not self._flusher_started:
                self._flusher_started = True
                atexit.register(self.flush)
                threading.Thread(target=self._flush_loop, daemon=True,
                                 name="art-trace-flush").start()
        if flush_now:
            self.flush()

    def _flush_loop(self) -> None:
        while True:
            time.sleep(_FLUSH_AGE_S)
            self.flush()

    def flush(self) -> None:
        """Batch-publish pending spans to the GCS span ring.  Best
        effort: outside a cluster the batch is dropped (the recorder
        stays a cheap local instrument).  Drivers/workers ship via the
        runtime's oneway channel; processes without one (the node
        daemon) install a publisher with :func:`set_publisher`."""
        with self._pending_lock:
            if not self._pending:
                return
            batch, self._pending = self._pending, []
            self._last_flush = time.monotonic()
        try:
            publisher = _publisher
            if publisher is not None:
                publisher(batch)
                return
            runtime = _runtime()
            if runtime is None:
                return
            runtime._send_oneway(runtime.gcs_address, "SpanEventsAdd",
                                 {"spans": batch})
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass

    def snapshot(self, limit: int = 0) -> list[dict]:
        """Ring contents (forced + sampled), start-time ordered."""
        spans = list(self._ring) + list(self._forced)
        spans.sort(key=lambda s: s.get("ts", 0.0))
        return spans[-limit:] if limit else spans

    def clear(self) -> None:
        self._ring.clear()
        self._forced.clear()
        with self._pending_lock:
            self._pending.clear()


_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()
_publisher = None


def set_publisher(fn) -> None:
    """Install the span-batch publisher for processes that are not art
    drivers/workers (the node daemon ships through its own GCS client).
    ``fn(batch: list[dict])`` must be thread-safe and non-blocking."""
    global _publisher
    _publisher = fn


def recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def flush() -> None:
    if _recorder is not None:
        _recorder.flush()


def _runtime():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if not global_worker.connected:
        return None
    runtime = global_worker.runtime
    return runtime if hasattr(runtime, "_send_oneway") else None


# ----------------------------------------------------------- span record

def record_span(ctx, name: str, *, ts: float, dur_s: float,
                stages: dict | None = None, attrs: dict | None = None,
                error: bool = False, span_id: str | None = None,
                parent_id: str | None = None,
                service: str = "") -> str | None:
    """Record one completed span under ``ctx`` (a TraceContext or wire
    tuple).  Unsampled contexts record nothing UNLESS ``error`` — error
    and shed spans are force-sampled into the recorder's protected ring
    (and still published, so a 429's trace id is findable).  Returns the
    span id (for callers chaining children explicitly)."""
    if isinstance(ctx, tuple):
        ctx = TraceContext.from_wire(ctx)
    if ctx is None:
        return None
    forced = error and not ctx.sampled
    if not ctx.sampled and not error:
        return None
    sid = span_id or f"{random.getrandbits(64):016x}"
    span = {
        "trace_id": ctx.trace_id,
        "span_id": sid,
        "parent_id": parent_id if parent_id is not None else ctx.span_id,
        "name": name,
        "ts": ts,
        "dur_s": dur_s,
        "node_id": _NODE_ID,
        "pid": _PID,
    }
    if stages:
        span["stages"] = stages
    if attrs:
        span["attrs"] = attrs
    if error:
        span["error"] = True
    if forced:
        span["forced"] = True
    if service:
        span["service"] = service
    recorder().record(span, forced=forced)
    return sid


class _Noop:
    """Span no-op for code paths with no trace context at all."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _Noop()


class _Span:
    """Live span block.  Unsampled contexts pay two perf_counter reads
    and this allocation; nothing is recorded unless the block raises
    (force-sampled error span)."""

    __slots__ = ("_ctx", "_name", "_attrs", "_t0", "span_id")

    def __init__(self, ctx: TraceContext, name: str, attrs: dict | None):
        self._ctx = ctx
        self._name = name
        self._attrs = attrs
        self.span_id = None

    def set(self, **attrs) -> None:
        if self._attrs is None:
            self._attrs = attrs
        else:
            self._attrs.update(attrs)

    def __enter__(self):
        # One clock read on entry; the wall-clock start is derived at
        # exit only when something is actually recorded (the unsampled
        # no-error path pays two perf_counter reads total).
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        ctx = self._ctx
        # GeneratorExit is a consumer abandoning a stream mid-yield —
        # a normal ending, not failure evidence to force-sample.
        error = (exc_type is not None
                 and not issubclass(exc_type, GeneratorExit))
        if ctx.sampled or error:
            dur = time.perf_counter() - self._t0
            # artlint: disable=banned-apis — span `ts` is a cross-
            # process wire field: wall clock is what lets spans from
            # different hosts land on one timeline.
            self.span_id = record_span(
                ctx, self._name, ts=time.time() - dur, dur_s=dur,
                attrs=self._attrs, error=error)
        return False


def span(name: str, attrs: dict | None = None):
    """``with tracing_plane.span("object:pull"):`` — record a child
    span of the active context (no-op without one; force-sampled on
    error even when unsampled)."""
    ctx = _current.get()
    if ctx is None:
        return _NOOP
    return _Span(ctx, name, attrs)


class server_span:
    """Traced-server-handler scaffold: ONE implementation of the
    time-the-block / record-span-and-rpc-observation-in-finally shape
    the daemon's traced handlers share.  Usage::

        with tracing_plane.server_span(wire, "daemon:lease",
                                       "LeaseWorker") as sp:
            reply = await impl(payload)
            sp.attrs = {...}
            sp.error = "infeasible" in reply

    An exception inside the block marks the span as an error
    automatically (GeneratorExit excepted); ``attrs``/``error`` set by
    the block ride the recorded span."""

    __slots__ = ("_wire", "_name", "_method", "_service", "attrs",
                 "error", "_wall", "_t0")

    def __init__(self, wire, name: str, method: str,
                 service: str = "node-daemon"):
        self._wire = wire
        self._name = name
        self._method = method
        self._service = service
        self.attrs: dict | None = None
        self.error = False

    def __enter__(self):
        self._wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None and not issubclass(exc_type,
                                                   GeneratorExit):
            self.error = True
        dur = time.perf_counter() - self._t0
        record_span(self._wire, self._name, ts=self._wall, dur_s=dur,
                    stages={"execute": dur}, attrs=self.attrs,
                    error=self.error, service=self._service)
        if self._wire:
            record_rpc(self._method, {"execute": dur}, self._wire[0])
        return False


# ----------------------------------------------- rpc latency histograms

_RPC_BOUNDARIES = [0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0]

_rpc_hist = None
_rpc_hist_lock = threading.Lock()
_metric_recorder = None


def set_metric_recorder(fn) -> None:
    """Install the histogram-observation sender for processes without a
    worker runtime (the node daemon ships ``MetricRecord`` payloads
    through its own GCS client).  ``fn(payload: dict)`` must be
    thread-safe and non-blocking."""
    global _metric_recorder
    _metric_recorder = fn


def _rpc_histogram():
    global _rpc_hist
    if _rpc_hist is None:
        with _rpc_hist_lock:
            if _rpc_hist is None:
                from ant_ray_tpu.util.metrics import Histogram  # noqa: PLC0415

                _rpc_hist = Histogram(
                    "art_rpc_latency_s",
                    "Per-stage RPC latency (client: serialize/wire; "
                    "server: queue/execute); exemplars carry trace ids",
                    boundaries=_RPC_BOUNDARIES,
                    tag_keys=("method", "stage"))
    return _rpc_hist


def record_rpc(method: str, stages: dict, trace_id: str = "") -> None:
    """Observe ``art_rpc_latency_s{method,stage}`` for one sampled RPC.
    Emitted only for sampled requests — the sampling rate bounds the
    metric traffic, and every observation carries the trace id as an
    OpenMetrics exemplar so a slow bucket links to a concrete trace."""
    try:
        recorder_fn = _metric_recorder
        if recorder_fn is not None:
            # Runtime-less process (node daemon): ship raw MetricRecord
            # payloads through the installed sender.
            for stage, seconds in stages.items():
                payload = {
                    "name": "art_rpc_latency_s", "type": "histogram",
                    "value": float(seconds),
                    "tags": {"method": method, "stage": stage},
                    "description": "Per-stage RPC latency",
                    "boundaries": _RPC_BOUNDARIES,
                }
                if trace_id:
                    payload["exemplar"] = {
                        "labels": {"trace_id": trace_id},
                        "value": float(seconds), "ts": time.time()}
                recorder_fn(payload)
            return
        hist = _rpc_histogram()
        exemplar = {"trace_id": trace_id} if trace_id else None
        for stage, seconds in stages.items():
            hist.observe(seconds, {"method": method, "stage": stage},
                         exemplar=exemplar)
    except Exception:  # noqa: BLE001 — observability must never fail a call
        pass


# ------------------------------------------------- method → plane table
#
# Every wire_schema METHODS entry must appear here (lint-enforced by
# tests/test_wire_schema.py): the plane label is the ``art_rpc_latency_s``
# aggregation axis a new RPC lands in, and the lint is what keeps a
# future RPC from shipping untraced — adding a method without deciding
# its plane fails CI.

RPC_METHOD_PLANES: dict[str, str] = {
    # ---- GCS control plane
    "RegisterNode": "control", "Heartbeat": "control",
    "GetAllNodes": "control", "ListNodes": "control",
    "GetScaleStats": "observability", "DrainNode": "control",
    "KVPut": "control", "KVGet": "control", "KVDel": "control",
    "KVTake": "control", "KVKeys": "control",
    "RegisterJob": "control", "CreateActor": "control",
    "GetActorInfo": "control", "WaitActorAlive": "control",
    "GetNamedActor": "control", "KillActor": "control",
    "ActorStateUpdate": "control", "WorkerDied": "control",
    "ObjectLocationAdd": "object", "ObjectLocationRemove": "object",
    "ObjectLocationsGet": "object", "FreeObject": "object",
    "SelectNode": "control", "ResourceDemands": "control",
    "AutoscalerHeartbeat": "control", "AutoscalingEnabled": "control",
    "ClusterResources": "control", "AvailableResources": "control",
    "CreatePlacementGroup": "control", "GetPlacementGroup": "control",
    "RemovePlacementGroup": "control", "ListPlacementGroups": "control",
    "ListActors": "control", "ListObjects": "object",
    "MetricRecord": "observability", "MetricsGet": "observability",
    "MetricsExpire": "observability",
    "CreateVirtualCluster": "control", "RemoveVirtualCluster": "control",
    "UpdateVirtualCluster": "control", "ListVirtualClusters": "control",
    "SetJobVirtualCluster": "control", "GetJobVirtualCluster": "control",
    "InsightRecord": "observability", "InsightGet": "observability",
    "TaskEventsAdd": "observability", "TaskEventsGet": "observability",
    "ListTasks": "observability", "GetTask": "observability",
    "SummarizeTasks": "observability", "ListJobs": "observability",
    "StepEventsAdd": "observability", "StepEventsGet": "observability",
    "SpanEventsAdd": "observability", "SpanEventsGet": "observability",
    "CpuProfileAdd": "observability", "CpuProfileGet": "observability",
    "SubPoll": "control", "PublishLogs": "observability",
    "ExportEventsGet": "observability", "Shutdown": "control",
    "GetHaView": "control",
    # ---- node daemon
    "LeaseWorker": "scheduling", "ReturnWorker": "scheduling",
    "RegisterWorker": "scheduling", "StartActorWorker": "scheduling",
    "KillActorWorker": "scheduling", "WorkerBlocked": "scheduling",
    "WorkerUnblocked": "scheduling", "PrepareBundle": "scheduling",
    "CommitBundle": "scheduling", "ReturnBundle": "scheduling",
    "CreateBuffer": "object", "SealBuffer": "object",
    "SealObject": "object", "DeleteObject": "object",
    "ContainsObject": "object", "LocateObject": "object",
    "ReadChunk": "object", "ReadChunkRaw": "object",
    "EnsureLocal": "object", "ReadDone": "object", "RenewPins": "object",
    "GetNodeInfo": "control", "NotifyDrain": "control",
    "DebugResources": "observability", "GetNodeMetrics": "observability",
    "GetStoreStats": "observability", "GetSyncStats": "observability",
    "ListObjectStats": "observability",
    "GetTransferStats": "observability",
    "GetFlightRecorder": "observability",
    "ListLogs": "observability", "ReadLog": "observability",
    # ---- worker / owner
    "PushTask": "execution", "CancelTask": "execution",
    "InstantiateActor": "execution", "Ping": "control",
    "GetObject": "object", "GetObjectStatus": "object",
    "GetObjectStatusBatch": "object", "WaitObjects": "object",
    "GetObjectInfo": "object", "GetOwnedRefInfo": "observability",
    "BorrowAdd": "object",
    "BorrowRemove": "object", "ReconstructObject": "object",
    "StreamItem": "execution", "DeviceTensorFetch": "object",
    "DeviceTensorFree": "object", "DeviceTensorSendVia": "object",
    # ---- node agent
    "BuildRuntimeEnv": "scheduling", "AgentListLogs": "observability",
    "AgentReadLog": "observability", "AgentMetrics": "observability",
    "AgentStats": "observability", "AgentDeviceStats": "observability",
    "AgentProfile": "observability", "GetAgentInfo": "control",
    # ---- store service (HA)
    "StorePut": "storage", "StoreGet": "storage",
    "StoreDelete": "storage", "StoreLoadTable": "storage",
    "LeaseAcquire": "storage", "LeaseRenew": "storage",
    "LeaseRelease": "storage", "LeaseInfo": "storage",
}


# ------------------------------------------------------------- tree view

def span_tree(spans: list[dict]) -> list[dict]:
    """Fold flat span dicts into a forest: each node is the span dict
    plus a ``children`` list (start-time ordered).  Spans whose parent
    is absent from the set (the ingress root, or a truncated ring)
    surface as roots — a partial trace still renders."""
    by_id = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots = []
    for node in sorted(by_id.values(), key=lambda s: s.get("ts", 0.0)):
        parent = by_id.get(node.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots
