"""Continuous whole-cluster CPU profiling (ref model: Google-Wide
Profiling / Parca-style always-on sampling, scaled down to stdlib).

Every process class in a cluster — driver, node daemons, workers, GCS
replicas, node agents — runs one background sampler thread that walks
``sys._current_frames()`` at ``cpu_profile_hz`` (default 67 Hz, a prime
that avoids lockstep with 10/100 ms periodic work) and folds each
thread's stack into a bounded ``{folded_stack: count}`` dict keyed by
(process class, thread role, frames).  Folded means the classic
flamegraph.pl collapsed format: semicolon-joined root-first frames, one
counter per distinct stack — aggregation is O(depth) per thread per
tick, no per-sample allocation beyond the key string.

Publication is the step/span-events idiom: every
``cpu_profile_publish_period_s`` the sampler ships the DELTA since its
last publish to the GCS ``CpuProfileAdd`` ring, best-effort oneway
(dropped outside a cluster).  Under HA each replica keeps its local
ring slice and ``CpuProfileGet`` merges at query time
(``gather_ring``, the sharded-ring discipline).  The same publish tick
rolls up :mod:`protocol`'s wire-accounting counters into
``art_rpc_bytes_total{method,direction}`` /
``art_rpc_frames_total{method}`` counter deltas through ``MetricRecord``
— per-node control-plane cost as a scrapeable series.

Cost model (enforced by ``benchmarks/microbench.py`` at
``cpu_profiler_overhead_fraction`` <= 0.02): the sampler holds the GIL
only inside one ``sys._current_frames()`` walk per tick; at 67 Hz with
typical stack depths the duty cycle is well under 2% of one core, and
``overhead_stats()`` reports the measured duty cycle so the budget is
checkable from inside any live process.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time

from ant_ray_tpu._private.config import global_config

# Frames deeper than this are truncated (leaf side kept): runaway
# recursion must not turn one sample into an unbounded key.
_MAX_DEPTH = 48
# Per-publish cap on distinct stacks in one record; the remainder is
# folded into a "(truncated)" bucket so a publish can never exceed a
# few tens of KB on the wire.
_PUBLISH_TOP_N = 200

_OVERFLOW_KEY = "(overflow)"

# Trailing instance numbers collapse so thread ROLES stay low-
# cardinality: "art-executor-3" and "ThreadPoolExecutor-0_1" are the
# same role as their siblings.
_ROLE_SUFFIX = re.compile(r"[-_]\d+([-_]\d+)*$")


def _role(thread_name: str) -> str:
    return _ROLE_SUFFIX.sub("", thread_name) or thread_name


def _frame_label(frame) -> str:
    code = frame.f_code
    base = os.path.basename(code.co_filename)
    if base.endswith(".py"):
        base = base[:-3]
    return f"{base}:{code.co_name}"


def _runtime():
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if not global_worker.connected:
        return None
    runtime = global_worker.runtime
    if getattr(runtime, "_gcs", None) is None:
        return None  # local mode
    return runtime if hasattr(runtime, "_send_oneway") else None


def _default_publish(record: dict) -> None:
    """Drivers/workers ship through the runtime's oneway channel; other
    process classes install their own publisher at :func:`start`."""
    runtime = _runtime()
    if runtime is not None:
        runtime._send_oneway(runtime.gcs_address, "CpuProfileAdd",
                             {"records": [record]})


def _default_metric(payload: dict) -> None:
    runtime = _runtime()
    if runtime is not None:
        runtime._send_oneway(runtime.gcs_address, "MetricRecord", payload)


class CpuProfiler:
    """One process's always-on sampling profiler.

    The sampler thread owns all mutable state — counting, delta
    bookkeeping and publication all happen on it, so the hot path takes
    no lock.  Readers (``snapshot``/``overhead_stats``) only ever copy,
    which the GIL makes atomic.
    """

    def __init__(self, process_class: str, *, hz: float | None = None,
                 publish_period_s: float | None = None,
                 max_stacks: int | None = None,
                 publish_fn=None, metric_fn=None, node_id: str = ""):
        cfg = global_config()
        self.process_class = process_class
        self.hz = float(cfg.cpu_profile_hz if hz is None else hz)
        self.publish_period_s = float(
            cfg.cpu_profile_publish_period_s
            if publish_period_s is None else publish_period_s)
        self.max_stacks = int(cfg.cpu_profile_max_stacks
                              if max_stacks is None else max_stacks)
        self.publish_fn = publish_fn
        self.metric_fn = metric_fn
        self.node_id = (node_id or os.environ.get("ART_NODE_ID", ""))[:12]
        self._stacks: dict[str, int] = {}
        self._last_published: dict[str, int] = {}
        self._samples = 0
        self._published_samples = 0
        self._sample_cost_ns = 0
        self._started_monotonic = 0.0
        self._last_publish_ts = time.time()
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- lifecycle

    def start(self) -> "CpuProfiler":
        if self._thread is None:
            self._started_monotonic = time.monotonic()
            self._last_publish_ts = time.time()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="art-cpu-profiler")
            self._thread.start()
        return self

    def stop(self, *, final_publish: bool = True) -> None:
        self._stop_event.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None
        if final_publish:
            try:
                self._publish()
            except Exception:  # noqa: BLE001 — observability best-effort
                pass

    # -------------------------------------------------------- sampling

    def _run(self) -> None:
        interval = 1.0 / self.hz if self.hz > 0 else 1.0
        next_publish = time.monotonic() + self.publish_period_s
        while not self._stop_event.wait(interval):
            try:
                self._sample()
            except Exception:  # noqa: BLE001 — a torn-down interpreter
                return         # during exit must not spew tracebacks
            if time.monotonic() >= next_publish:
                next_publish = time.monotonic() + self.publish_period_s
                try:
                    self._publish()
                except Exception:  # noqa: BLE001 — best-effort
                    pass

    def _sample(self) -> None:
        t0 = time.perf_counter_ns()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # never profile the profiler
            parts = []
            depth = 0
            while frame is not None and depth < _MAX_DEPTH:
                parts.append(_frame_label(frame))
                frame = frame.f_back
                depth += 1
            parts.reverse()
            role = _role(names.get(tid) or f"tid-{tid}")
            self._count(
                f"{self.process_class};{role};" + ";".join(parts))
        self._samples += 1
        self._sample_cost_ns += time.perf_counter_ns() - t0

    def _count(self, key: str, n: int = 1) -> None:
        stacks = self._stacks
        if key in stacks:
            stacks[key] += n
        elif len(stacks) < self.max_stacks:
            stacks[key] = n
        else:  # bounded: novel stacks collapse into one bucket
            overflow = f"{self.process_class};{_OVERFLOW_KEY}"
            stacks[overflow] = stacks.get(overflow, 0) + n

    # ----------------------------------------------------- publication

    def _publish(self) -> None:
        record = self._delta_record()
        if record is not None:
            publish = self.publish_fn or _default_publish
            try:
                publish(record)
            except Exception:  # noqa: BLE001 — best-effort
                pass
        try:
            self._publish_wire_metrics()
        except Exception:  # noqa: BLE001 — best-effort
            pass

    def _delta_record(self) -> dict | None:
        current = self._stacks.copy()
        delta: dict[str, int] = {}
        for key, count in current.items():
            d = count - self._last_published.get(key, 0)
            if d > 0:
                delta[key] = d
        self._last_published = current
        now = time.time()
        dur_s, self._last_publish_ts = now - self._last_publish_ts, now
        if not delta:
            return None
        if len(delta) > _PUBLISH_TOP_N:
            ranked = sorted(delta.items(), key=lambda kv: (-kv[1], kv[0]))
            kept = dict(ranked[:_PUBLISH_TOP_N])
            dropped = sum(delta.values()) - sum(kept.values())
            if dropped:
                truncated = f"{self.process_class};(truncated)"
                kept[truncated] = kept.get(truncated, 0) + dropped
            delta = kept
        total = self._samples
        samples = total - self._published_samples
        self._published_samples = total
        return {"node_id": self.node_id, "pid": os.getpid(),
                "proc": self.process_class, "ts": now, "dur_s": dur_s,
                "hz": self.hz, "samples": samples, "stacks": delta}

    def _publish_wire_metrics(self) -> None:
        from ant_ray_tpu._private import protocol  # noqa: PLC0415

        deltas = protocol.wire_deltas()
        if not deltas:
            return
        metric = self.metric_fn or _default_metric
        node = self.node_id
        frames_by_method: dict[str, int] = {}
        for (method, direction), (frames, nbytes, encode_ns) in \
                deltas.items():
            frames_by_method[method] = \
                frames_by_method.get(method, 0) + frames
            if nbytes:
                metric({"name": "art_rpc_bytes_total", "type": "counter",
                        "value": float(nbytes),
                        "tags": {"method": method,
                                 "direction": direction,
                                 "node_id": node},
                        "description": "Wire bytes moved per RPC "
                                       "method and direction"})
            if encode_ns:
                metric({"name": "art_rpc_encode_seconds_total",
                        "type": "counter", "value": encode_ns / 1e9,
                        "tags": {"method": method, "node_id": node},
                        "description": "Client-side frame-encode time "
                                       "per RPC method"})
        for method, frames in frames_by_method.items():
            if frames:
                metric({"name": "art_rpc_frames_total",
                        "type": "counter", "value": float(frames),
                        "tags": {"method": method, "node_id": node},
                        "description": "Wire frames moved per RPC "
                                       "method"})
        for method, (calls, handle_ns) in \
                protocol.handle_deltas().items():
            if handle_ns:
                metric({"name": "art_rpc_handle_seconds_total",
                        "type": "counter", "value": handle_ns / 1e9,
                        "tags": {"method": method, "node_id": node},
                        "description": "Server-side dispatch-to-reply "
                                       "time per RPC method"})
            if calls:
                metric({"name": "art_rpc_handled_total",
                        "type": "counter", "value": float(calls),
                        "tags": {"method": method, "node_id": node},
                        "description": "Server-side dispatches per "
                                       "RPC method"})

    # --------------------------------------------------------- reading

    def snapshot(self) -> dict[str, int]:
        """Cumulative folded stacks since start (copy; GIL-atomic)."""
        return self._stacks.copy()

    def overhead_stats(self) -> dict:
        """Measured sampler duty cycle — the <2% budget, checkable live."""
        wall = max(time.monotonic() - self._started_monotonic, 1e-9)
        samples = max(self._samples, 1)
        cost_s = self._sample_cost_ns / 1e9
        return {"samples": self._samples,
                "avg_sample_cost_s": cost_s / samples,
                "overhead_fraction": cost_s / wall}


# -------------------------------------------------- process singleton

_profiler: CpuProfiler | None = None
_profiler_lock = threading.Lock()


def start(process_class: str, *, publish_fn=None, metric_fn=None,
          node_id: str = "", hz: float | None = None,
          publish_period_s: float | None = None) -> CpuProfiler | None:
    """Start this process's profiler (idempotent).  Returns None when
    ``cpu_profile_hz`` (or the explicit ``hz``) is 0 — the whole plane
    off-switch."""
    global _profiler
    effective_hz = global_config().cpu_profile_hz if hz is None else hz
    if effective_hz <= 0:
        return None
    with _profiler_lock:
        if _profiler is None:
            _profiler = CpuProfiler(
                process_class, hz=hz, publish_period_s=publish_period_s,
                publish_fn=publish_fn, metric_fn=metric_fn,
                node_id=node_id).start()
        return _profiler


def stop() -> None:
    global _profiler
    with _profiler_lock:
        prof, _profiler = _profiler, None
    if prof is not None:
        prof.stop()


def profiler() -> CpuProfiler | None:
    return _profiler


# ------------------------------------------------ folded-stack algebra

def merge_folded(records) -> dict[str, int]:
    """Sum the ``stacks`` dicts of CpuProfile ring records into one
    folded-stack aggregate."""
    merged: dict[str, int] = {}
    for record in records:
        for key, count in (record.get("stacks") or {}).items():
            merged[key] = merged.get(key, 0) + int(count)
    return merged


def render_folded(stacks: dict[str, int]) -> str:
    """Collapsed-stack text: ``stack count`` lines, heaviest first —
    pipe straight into flamegraph.pl or import into speedscope."""
    lines = [f"{key} {count}" for key, count in
             sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines)


def self_time(stacks: dict[str, int]) -> dict[str, int]:
    """Per-frame SELF samples: each folded stack's count lands on its
    leaf frame only."""
    out: dict[str, int] = {}
    for key, count in stacks.items():
        leaf = key.rsplit(";", 1)[-1]
        out[leaf] = out.get(leaf, 0) + int(count)
    return out


def diff_folded(a_stacks: dict[str, int],
                b_stacks: dict[str, int]) -> list[tuple[str, int, int, int]]:
    """Rank frames by self-time delta, B minus A: the A/B answer to
    "what got more expensive".  Returns ``(frame, delta, a, b)`` rows,
    biggest regression first, biggest improvement last."""
    a_self = self_time(a_stacks)
    b_self = self_time(b_stacks)
    rows = []
    for frame in set(a_self) | set(b_self):
        a = a_self.get(frame, 0)
        b = b_self.get(frame, 0)
        if a != b:
            rows.append((frame, b - a, a, b))
    rows.sort(key=lambda r: (-r[1], r[0]))
    return rows
