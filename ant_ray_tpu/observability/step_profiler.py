"""Per-step phase profiler for training/inference loops.

One instrument, three consumers (the task-events pattern):

* the loop itself — ``profiler.last`` / ``profiler.summary()`` for
  logging and adaptive behavior;
* Train — ``session.report()`` auto-attaches the latest record, the
  controller aggregates across ranks into Prometheus gauges
  (step-time mean/p50/max, phase fractions, straggler ratio);
* the timeline — records are batch-published to the GCS step-events
  table and ``util/timeline.py`` merges them as per-rank device rows
  next to the task schedule.

Phases are attributions, not a schedule: ``data_wait`` (blocked on the
input pipeline), ``h2d`` (host→HBM transfer), ``collective``
(cross-rank sync incl. pack/unpack), and ``compute`` — which, unless
explicitly timed, is derived as the un-attributed remainder of the
step.  Phase seconds come from two sources that never double-instrument:

* explicit ``with profiler.phase("data_wait"):`` blocks;
* attached stats streams — a device-feed iterator
  (``data/device_feed.py``) contributes its ``consumer_starve_s`` /
  ``transfer_issue_s`` deltas, a collective group's fusion stats
  (``util/collective/fusion.py``) contribute pack/transfer/collective
  deltas — so the PR-2/PR-3 stats idioms become phases of THIS stream
  instead of parallel vocabularies.

Cost model (enforced by ``benchmarks/microbench.py`` at < 2 µs/step):
the step path is two ``perf_counter`` reads, a wall-clock read, and a
raw ``(step, ts, total, phases)`` tuple appended to a bounded deque —
records materialize into :class:`StepRecord` objects and the MFU /
compute-remainder math runs only when something *reads* them (``last``,
``summary()``, a batch flush).  Publishing is batched off the step path
and silently dropped when no cluster is connected — like
``util/metrics._record``, telemetry is best-effort, never a dependency.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

PHASES = ("data_wait", "h2d", "compute", "collective")

# device_feed stat key -> phase it attributes to
_FEED_PHASE_KEYS = (("consumer_starve_s", "data_wait"),
                    ("transfer_issue_s", "h2d"))
# fusion stat key -> phase (pack/unpack are host work *for* the
# collective; transfer is the bucket's host→device hop).  overlap_s
# (collective time hidden under backward compute by the ready-hook
# GradientSyncer) carries weight -1: the collective phase reports only
# the EXPOSED communication time.
_FUSION_PHASE_KEYS = (("pack_s", "collective"), ("unpack_s", "collective"),
                      ("collective_s", "collective"), ("transfer_s", "h2d"))
_FUSION_NEGATIVE_KEYS = (("overlap_s", "collective"),)


@dataclass
class StepRecord:
    """One completed step: wall-clock placement + phase attribution."""

    step: int
    start_ts: float                  # wall clock (time.time) at entry
    total_s: float
    phases: dict                     # phase -> seconds (attributed)
    mfu: float | None = None
    rank: int = 0

    def fraction(self, phase: str) -> float:
        if self.total_s <= 0:
            return 0.0
        return min(1.0, self.phases.get(phase, 0.0) / self.total_s)

    def as_dict(self) -> dict:
        return {"step": self.step, "ts": self.start_ts,
                "total_s": self.total_s, "phases": dict(self.phases),
                "mfu": self.mfu, "rank": self.rank}

    @classmethod
    def from_dict(cls, d: dict) -> "StepRecord":
        return cls(step=int(d.get("step", 0)),
                   start_ts=float(d.get("ts", 0.0)),
                   total_s=float(d.get("total_s", 0.0)),
                   phases=dict(d.get("phases") or {}),
                   mfu=d.get("mfu"), rank=int(d.get("rank", 0)))


class _PhaseTimer:
    """Reusable context manager — one per phase name, allocated once."""

    __slots__ = ("_prof", "_name", "_t0")

    def __init__(self, prof: "StepProfiler", name: str):
        self._prof = prof
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        phases = self._prof._cur_phases
        phases[self._name] = (phases.get(self._name, 0.0)
                              + time.perf_counter() - self._t0)
        return False


class StepProfiler:
    """Record per-step phase timings; see the module docstring.

    Usage::

        prof = StepProfiler(flops_per_step=model_flops)
        prof.attach_data_iterator(it)        # data_wait/h2d from stats
        for batch in it.iter_device_batches(batch_size=64):
            with prof.step():
                grads = step_fn(params, batch)          # -> compute
                grads = train.sync_gradients(grads)     # -> collective
            train.report({"loss": ...})      # step record auto-attached

    ``train.sync_gradients`` auto-attaches its gang's fusion stats, so
    collective/h2d attribution is already covered — do NOT also wrap it
    in an explicit ``phase("collective")`` block (each second of sync
    would be attributed twice).  Explicit phase blocks are for code the
    profiler cannot see into (a custom data fetch, a manual
    ``all_reduce``).

    ``flops_per_step`` enables MFU: achieved flops / the detected TPU
    peak (``_private/accelerators/tpu.py`` hardware table × bound
    chips), or an explicit ``peak_flops`` override (required for a
    meaningful MFU off-TPU).
    """

    __slots__ = ("_flops_per_step", "_peak_flops", "records", "_publish",
                 "_publish_batch", "_pending", "_step_index",
                 "_cur_phases", "_t0", "_wall0", "_timers",
                 "_feed_stats", "_fusion_fns", "_rank")

    def __init__(self, *, flops_per_step: float | None = None,
                 peak_flops: float | None = None, history: int = 256,
                 publish: bool = True, publish_batch: int = 64):
        from collections import deque  # noqa: PLC0415

        self._flops_per_step = flops_per_step
        self._peak_flops = (peak_flops if peak_flops is not None
                            else self._detect_peak_flops())
        # raw (step, wall_ts, total_s, phases) tuples — materialized
        # into StepRecords only on read, keeping the step path cheap
        self.records: Any = deque(maxlen=max(1, history))
        self._publish = publish
        self._publish_batch = max(1, publish_batch)
        self._pending: list[tuple] = []
        self._step_index = 0
        self._cur_phases: dict[str, float] = {}
        self._t0 = 0.0
        self._wall0 = 0.0
        self._timers: dict[str, _PhaseTimer] = {}
        self._feed_stats: list[dict] = []
        self._fusion_fns: list[dict] = []
        self._rank = 0
        # Register on the train context (if inside a worker loop) so
        # session.report() can auto-attach the latest record.
        try:
            from ant_ray_tpu.train.session import get_context  # noqa: PLC0415

            ctx = get_context()
            ctx.step_profiler = self
            self._rank = ctx.world_rank
        except Exception:  # noqa: BLE001 — plain script, no train loop
            pass

    # ------------------------------------------------------- attachment

    def attach_data_iterator(self, iterator) -> "StepProfiler":
        """Absorb a DataIterator/DeviceFeed stats stream: per-step
        deltas of ``consumer_starve_s`` → data_wait and
        ``transfer_issue_s`` → h2d.  The stats are re-read every step
        (``DataIterator.stats()`` returns a fresh snapshot, and before
        iteration starts it has no device_feed section at all)."""
        if callable(getattr(iterator, "stats", None)):
            def fn(it=iterator):
                stats = it.stats()
                return stats.get("device_feed", {}) \
                    if isinstance(stats, dict) else {}
        else:                        # a live stats dict (or DeviceFeed)
            def fn(live=iterator):
                return live.get("device_feed", live) \
                    if isinstance(live, dict) else live.stats
        self._feed_stats.append({"fn": fn, "snap": dict(fn())})
        return self

    def attach_fusion_stats(self, group_name: str = "default"
                            ) -> "StepProfiler":
        """Absorb a collective group's fusion stats: per-step deltas of
        pack/unpack/collective seconds → collective, transfer → h2d."""
        from ant_ray_tpu.util import collective as col  # noqa: PLC0415

        def fn(name=group_name):
            try:
                return col.fusion_stats(name)
            except Exception:  # noqa: BLE001 — group torn down mid-run
                return {}

        self._fusion_fns.append({"fn": fn, "snap": dict(fn())})
        return self

    @staticmethod
    def _detect_peak_flops() -> float | None:
        from ant_ray_tpu._private.accelerators import tpu as tpu_accel  # noqa: PLC0415

        gen = tpu_accel.detect_generation()
        if gen is None:
            return None             # off-TPU: MFU needs peak_flops=
        chips = max(1, tpu_accel.num_tpu_chips())
        return tpu_accel.peak_bf16_tflops(gen) * 1e12 * chips

    # -------------------------------------------------------- step path

    def step(self) -> "StepProfiler":
        """``with profiler.step():`` wraps exactly one training step."""
        return self

    def __enter__(self):
        self._cur_phases = {}
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        total = time.perf_counter() - self._t0
        phases = self._cur_phases
        if self._feed_stats or self._fusion_fns:    # attached streams
            self._merge_stream_deltas(phases)
        rec = (self._step_index, self._wall0, total, phases)
        self._step_index += 1
        self.records.append(rec)
        if self._publish:
            pending = self._pending
            pending.append(rec)
            if len(pending) >= self._publish_batch:
                self.flush()
        return False

    def phase(self, name: str) -> _PhaseTimer:
        """``with profiler.phase("data_wait"):`` attributes the block's
        wall time to that phase (names outside PHASES are allowed and
        reported verbatim)."""
        timer = self._timers.get(name)
        if timer is None:
            timer = self._timers[name] = _PhaseTimer(self, name)
        return timer

    def _merge_stream_deltas(self, phases: dict) -> None:
        for keys, entries in ((_FEED_PHASE_KEYS, self._feed_stats),
                              (_FUSION_PHASE_KEYS, self._fusion_fns)):
            for entry in entries:
                live, snap = entry["fn"](), entry["snap"]
                for key, phase in keys:
                    value = live.get(key, 0.0)
                    delta = value - snap.get(key, 0.0)
                    if delta > 0:
                        phases[phase] = phases.get(phase, 0.0) + delta
                    snap[key] = value
        for entry in self._fusion_fns:
            live, snap = entry["fn"](), entry["snap"]
            for key, phase in _FUSION_NEGATIVE_KEYS:
                value = live.get(key, 0.0)
                delta = value - snap.get(key, 0.0)
                if delta > 0 and phase in phases:
                    # Compute-hidden share: subtract, never below zero.
                    phases[phase] = max(0.0, phases[phase] - delta)
                snap[key] = value

    # -------------------------------------------------- materialization

    def _raw_to_dict(self, raw: tuple) -> dict:
        step, wall0, total, phases = raw
        phases = dict(phases)
        if "compute" not in phases:
            # The un-attributed remainder is the device-bound part.
            phases["compute"] = max(0.0, total - sum(phases.values()))
        mfu = None
        if self._flops_per_step and self._peak_flops and total > 0:
            mfu = self._flops_per_step / (total * self._peak_flops)
        return {"step": step, "ts": wall0, "total_s": total,
                "phases": phases, "mfu": mfu, "rank": self._rank}

    def _materialize(self, raw: tuple) -> StepRecord:
        return StepRecord.from_dict(self._raw_to_dict(raw))

    # ------------------------------------------------------- publishing

    def flush(self) -> None:
        """Batch-publish pending records to the GCS step-events table.
        Best-effort: outside a cluster the batch is dropped (the
        profiler stays a cheap local instrument, metrics-style)."""
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        try:
            from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

            if not global_worker.connected:
                return
            runtime = global_worker.runtime
            if getattr(runtime, "_gcs", None) is None:
                return              # local mode
            runtime._send_oneway(
                runtime.gcs_address, "StepEventsAdd",
                {"records": [self._raw_to_dict(r) for r in batch]})
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass

    # --------------------------------------------------------- analysis

    @property
    def last(self) -> StepRecord | None:
        return self._materialize(self.records[-1]) if self.records \
            else None

    def step_records(self) -> list[StepRecord]:
        """The retained window as materialized records."""
        return [self._materialize(r) for r in self.records]

    def summary(self) -> dict:
        """Aggregate over the retained window: step-time mean/p50/max,
        mean phase fractions, mean MFU."""
        records = self.step_records()
        if not records:
            return {"steps": 0}
        times = sorted(r.total_s for r in records)
        n = len(times)
        out: dict = {
            "steps": records[-1].step + 1,
            "window": n,
            "step_time_mean_s": sum(times) / n,
            "step_time_p50_s": (times[(n - 1) // 2] + times[n // 2]) / 2,
            "step_time_max_s": times[-1],
        }
        names: set = set()
        for r in records:
            names.update(r.phases)
        for name in sorted(names):
            out[f"phase_{name}_fraction"] = (
                sum(r.fraction(name) for r in records) / n)
        mfus = [r.mfu for r in records if r.mfu is not None]
        if mfus:
            out["mfu_mean"] = sum(mfus) / len(mfus)
        return out

    def close(self) -> None:
        self.flush()
