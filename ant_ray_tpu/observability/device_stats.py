"""Per-device memory (HBM) statistics via ``jax.Device.memory_stats()``.

Parity target: the reference's per-node GPU/GRAM gauges from the
metrics agent (ref: dashboard/modules/reporter) — here TPU-native:
``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` per chip
from the PJRT allocator, with a graceful degrade everywhere stats do
not exist (CPU backends return ``None``; a chip locked by another
process yields an empty list rather than an exception).

Consumers: the node agent serves these on demand (``AgentStats`` /
``AgentDeviceStats``) and publishes them into the GCS metrics table on
an interval so ``/metrics`` exposes ``art_device_hbm_*`` gauges; a
training loop can snapshot them directly for step records.
"""

from __future__ import annotations

_STAT_KEYS = (
    ("bytes_in_use", "bytes_in_use"),
    ("peak_bytes_in_use", "peak_bytes_in_use"),
    ("bytes_limit", "bytes_limit"),
    # some PJRT plugins spell the pool ceiling differently
    ("bytes_limit", "pool_bytes"),
)


def _devices():
    try:
        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

        jax = import_jax()
        return jax.local_devices()
    except Exception:  # noqa: BLE001 — no jax / no usable backend
        return []


def device_memory_stats(devices=None) -> list[dict]:
    """One entry per local device.  ``bytes_*`` fields are ints where
    the backend reports them and ``None`` where it does not (CPU) —
    the CPU-graceful contract callers rely on."""
    out = []
    for i, dev in enumerate(_devices() if devices is None else devices):
        entry: dict = {
            "index": i,
            "device": str(dev),
            "platform": getattr(dev, "platform", "unknown"),
            "bytes_in_use": None,
            "peak_bytes_in_use": None,
            "bytes_limit": None,
        }
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend without the API
            stats = None
        if stats:
            for field, key in _STAT_KEYS:
                if entry[field] is None and stats.get(key) is not None:
                    entry[field] = int(stats[key])
        out.append(entry)
    return out


def device_stats_gauges(stats: list[dict] | None = None) -> list[dict]:
    """Prometheus-shaped gauge series (the node-metrics wire format:
    name/type/value/tags/description).  Devices without memory stats
    (CPU) contribute nothing — scrapes stay clean off-TPU."""
    if stats is None:
        stats = device_memory_stats()
    series = []
    for entry in stats:
        tags = {"device": str(entry.get("device", entry.get("index"))),
                "platform": entry.get("platform", "unknown")}
        for field, name, desc in (
                ("bytes_in_use", "art_device_hbm_bytes_in_use",
                 "device memory currently allocated"),
                ("peak_bytes_in_use", "art_device_hbm_peak_bytes",
                 "high-water device memory"),
                ("bytes_limit", "art_device_hbm_bytes_limit",
                 "device memory capacity")):
            value = entry.get(field)
            if value is None:
                continue
            series.append({"name": name, "type": "gauge",
                           "value": float(value), "tags": dict(tags),
                           "description": desc})
    return series
