"""ant_ray_tpu — a TPU-native distributed computing framework.

Tasks, actors, and a distributed object plane (the capability surface of
antgroup/ant-ray) re-designed for TPU clusters: XLA collectives over ICI/DCN,
HBM as a first-class object-store tier, slice/topology-aware gang scheduling,
and parallelism strategies (DP/FSDP/TP/PP/EP + ring-attention / Ulysses
sequence parallelism) expressed as JAX/pjit/Pallas sharding programs.
"""

from ant_ray_tpu.api import (
    ClientContext,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from ant_ray_tpu.object_ref import ObjectRef
from ant_ray_tpu.remote_function import RemoteFunction
from ant_ray_tpu.actor import ActorClass, ActorHandle
from ant_ray_tpu import exceptions

__version__ = "0.1.0"

__all__ = [
    "ActorClass",
    "ActorHandle",
    "ClientContext",
    "ObjectRef",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "get",
    "get_actor",
    "init",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
]
