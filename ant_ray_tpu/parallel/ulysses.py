"""Ulysses-style sequence parallelism: all-to-all head scatter.

The sequence-sharded activations are re-sharded so each device holds the
*full* sequence for a *subset of heads* (one `all_to_all` on the sp axis),
attention runs locally per head group, and a second all_to_all restores
sequence sharding.  Complements ring attention: Ulysses moves activations
twice but runs attention unblocked (better for moderate sequence lengths);
ring never materializes the full sequence (better for extreme lengths).

Net-new vs the reference (no sequence parallelism exists there).
"""

from __future__ import annotations

import functools

from ant_ray_tpu._private.jax_utils import import_jax
from ant_ray_tpu.parallel.ring import reference_attention


def _shard_map():
    from ant_ray_tpu._private.jax_utils import shard_map  # noqa: PLC0415

    return shard_map()


def ulysses_attention_kernel(q, k, v, *, axis_name: str, axis_size: int,
                             causal: bool = True,
                             scale: float | None = None,
                             attn_fn=None):
    """Per-device Ulysses attention (call inside shard_map).

    q: (batch, seq_local, heads, head_dim); heads must be divisible by
    axis_size.  attn_fn(q, k, v, causal, scale) runs full local attention;
    defaults to the exact reference implementation (swap in a flash
    kernel for production).
    """
    jax = import_jax()
    from jax import lax  # noqa: PLC0415

    attn_fn = attn_fn or (
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal=causal,
                                               scale=scale))
    num_heads = q.shape[2]
    num_kv_heads = k.shape[2]
    if num_heads % axis_size != 0:
        raise ValueError(
            f"heads {num_heads} not divisible by sp axis {axis_size}")
    if num_kv_heads % axis_size != 0:
        raise ValueError(
            f"kv heads {num_kv_heads} not divisible by sp axis {axis_size}")

    def scatter_heads(x):
        # (b, s_local, h, d) → (b, s_global, h/axis, d)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        # (b, s_global, h/axis, d) → (b, s_local, h, d)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(scatter_heads(q), scatter_heads(k), scatter_heads(v))
    return gather_heads(out)


def ulysses_attention(q, k, v, *, mesh, axis_name: str = "sp",
                      causal: bool = True, scale: float | None = None,
                      batch_axes=("dp", "fsdp")):
    """Standalone sharded Ulysses attention over global arrays (heads are
    NOT tp-sharded here: the sp axis claims the head dimension)."""
    jax = import_jax()
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    axis_size = mesh.shape[axis_name]
    spec = P(batch_axes, axis_name, None, None)
    kernel = functools.partial(
        ulysses_attention_kernel, axis_name=axis_name, axis_size=axis_size,
        causal=causal, scale=scale)
    fn = _shard_map()(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    return jax.jit(fn)(q, k, v)
