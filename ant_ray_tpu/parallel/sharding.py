"""Logical-axis sharding rules → NamedShardings.

Parallelism strategies (DP / FSDP / TP / EP — SURVEY §2.3) are expressed as
a mapping from *logical* tensor dimensions ("batch", "embed", "heads", …)
to mesh axes, so one model definition serves every strategy by swapping
rule tables (the idiomatic pjit recipe; contrast with the reference where
DP is torch-DDP actors and TP/PP are vLLM config passthrough).
"""

from __future__ import annotations

from typing import Any, Sequence

from ant_ray_tpu._private.jax_utils import import_jax

# A rule maps a logical dim name to: None (replicate), one mesh axis, or a
# tuple of mesh axes (dimension sharded over their product).
LogicalAxisRules = dict[str, Any]

# Llama-family rules: batch over (dp, fsdp); sequence over sp; attention
# heads and mlp hidden over tp; params sharded over fsdp on one dim
# (ZeRO-style) and tp on the parallel dim.
DEFAULT_LLAMA_RULES: LogicalAxisRules = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": None,
    "embed_param": "fsdp",       # param dim sharded for FSDP/ZeRO
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "experts": "ep",
    "norm": None,
}


def logical_to_spec(logical_dims: Sequence[str | None],
                    rules: LogicalAxisRules | None = None):
    """("batch","seq","embed") → PartitionSpec(("dp","fsdp"), "sp", None)."""
    from jax.sharding import PartitionSpec  # noqa: PLC0415

    rules = rules if rules is not None else DEFAULT_LLAMA_RULES
    parts = []
    for dim in logical_dims:
        if dim is None:
            parts.append(None)
        else:
            if dim not in rules:
                raise KeyError(f"no sharding rule for logical dim {dim!r}")
            parts.append(rules[dim])
    return PartitionSpec(*parts)


def named_sharding(mesh, logical_dims: Sequence[str | None],
                   rules: LogicalAxisRules | None = None):
    from jax.sharding import NamedSharding  # noqa: PLC0415

    return NamedSharding(mesh, logical_to_spec(logical_dims, rules))


def shard_pytree(tree, logical_tree, mesh,
                 rules: LogicalAxisRules | None = None):
    """Device-put a pytree of arrays according to a parallel pytree of
    logical dim tuples; logical leaves of None mean replicate."""
    jax = import_jax()

    def _place(x, dims):
        if dims is None:
            dims = (None,) * getattr(x, "ndim", 0)
        return jax.device_put(x, named_sharding(mesh, dims, rules))

    return jax.tree.map(_place, tree, logical_tree,
                        is_leaf=lambda x: x is None)


def pytree_shardings(tree, logical_tree, mesh,
                     rules: LogicalAxisRules | None = None):
    """NamedSharding pytree (for jit in_shardings/out_shardings)."""

    def _spec(x, dims):
        if dims is None:
            dims = (None,) * getattr(x, "ndim", 0)
        return named_sharding(mesh, dims, rules)

    jax = import_jax()
    return jax.tree.map(_spec, tree, logical_tree,
                        is_leaf=lambda x: x is None)


def constrain(x, logical_dims: Sequence[str | None],
              rules: LogicalAxisRules | None = None):
    """In-jit sharding constraint by logical dims (mesh from context)."""
    jax = import_jax()
    from jax.lax import with_sharding_constraint  # noqa: PLC0415

    return with_sharding_constraint(
        x, logical_to_spec(logical_dims, rules))
