"""Pipeline parallelism: SPMD GPipe schedule over a ``pp`` mesh axis.

The reference provides PP only as vLLM config passthrough plus compiled-DAG
actor microbatching (SURVEY §2.3); here it is a single compiled XLA
program: every stage runs the same shard_map kernel, activations hop one
station per tick via ``ppermute``, bubbles are masked.  This composes with
the other axes (dp/fsdp/tp/sp) because it is just another mesh dimension.

Restriction (GPipe-standard): every stage preserves the activation
shape/dtype — true for transformer blocks.
"""

from __future__ import annotations

import functools

from ant_ray_tpu._private.jax_utils import import_jax


def _shard_map():
    from ant_ray_tpu._private.jax_utils import shard_map  # noqa: PLC0415

    return shard_map()


def gpipe_kernel(stage_fn, stage_params, microbatches, *, axis_name: str,
                 axis_size: int):
    """Per-device GPipe (call inside shard_map).

    stage_params: this stage's params with leading stage dim of 1
                  (tree_map-squeezed before use).
    microbatches: (num_micro, ...) — identical on every stage (replicated).
    Returns (num_micro, ...) final-stage outputs, replicated to all stages.
    """
    jax = import_jax()
    import jax.numpy as jnp  # noqa: PLC0415
    from jax import lax  # noqa: PLC0415

    params = jax.tree.map(lambda p: p[0], stage_params)
    idx = lax.axis_index(axis_name)
    num_micro = microbatches.shape[0]
    ticks = num_micro + axis_size - 1

    # Forward-shift permutation: stage i → i+1 (last stage's send drops
    # into stage 0, which ignores it).
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def tick(carry, t):
        pending = carry                       # activation from prev stage
        x_first = jnp.take(microbatches, jnp.clip(t, 0, num_micro - 1),
                           axis=0)
        x_in = jnp.where(idx == 0, x_first, pending)
        active = (t - idx >= 0) & (t - idx < num_micro)
        y = stage_fn(params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        return lax.ppermute(y, axis_name, perm), y

    # The carry becomes pp-varying after the first ppermute; mark the
    # initial value accordingly (microbatches are replicated over pp).
    zeros0 = jnp.zeros_like(microbatches[0])
    if hasattr(lax, "pcast"):          # jax >= the pvary deprecation
        pending0 = lax.pcast(zeros0, axis_name, to="varying")
    elif hasattr(lax, "pvary"):        # the pvary window
        pending0 = lax.pvary(zeros0, axis_name)
    else:
        # jax predating varying-axes typing: there is no replicated vs.
        # varying distinction to annotate — the carry is just a value.
        pending0 = zeros0
    _, stage_outs = lax.scan(tick, pending0, jnp.arange(ticks))

    # Microbatch j leaves the last stage at tick j + axis_size - 1;
    # broadcast the last stage's tick outputs to everyone and slice.
    all_outs = lax.all_gather(stage_outs, axis_name)      # (pp, T, ...)
    last = jnp.take(all_outs, axis_size - 1, axis=0)      # (T, ...)
    return lax.dynamic_slice_in_dim(last, axis_size - 1, num_micro, axis=0)


def gpipe(stage_fn, stacked_params, microbatches, *, mesh,
          axis_name: str = "pp", batch_axes=("dp", "fsdp"),
          extra_activation_specs=None):
    """Run a GPipe pipeline over global arrays.

    Args:
      stage_fn: (params, x) -> y with y.shape == x.shape.
      stacked_params: pytree whose leaves have leading dim == pp degree
        (stage i's params at index i); sharded over the pp axis.
      microbatches: (num_micro, batch, ...) inputs; batch sharded over
        ``batch_axes``, replicated over pp.
    """
    jax = import_jax()
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    axis_size = mesh.shape[axis_name]
    param_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    x_spec = P(None, batch_axes)
    kernel = functools.partial(gpipe_kernel, stage_fn,
                               axis_name=axis_name, axis_size=axis_size)
    shard_map = _shard_map()
    # The final all_gather+take replicates the output over pp, but the
    # varying-axes checker can't infer that statically — disable it.
    try:
        fn = shard_map(kernel, mesh=mesh, in_specs=(param_spec, x_spec),
                       out_specs=x_spec, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        fn = shard_map(kernel, mesh=mesh, in_specs=(param_spec, x_spec),
                       out_specs=x_spec, check_rep=False)
    return jax.jit(fn)(stacked_params, microbatches)
