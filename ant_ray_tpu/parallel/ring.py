"""Ring attention: exact attention over sequence shards with the KV blocks
rotating around the ICI ring (`ppermute`), flash-style online softmax so
memory stays O(seq_local).

This is net-new capability vs the reference (SURVEY §2.3: no sequence /
context parallelism anywhere in ant-ray; its long-context story is
delegated to vLLM).  Design follows the blockwise-parallel / ring attention
formulation: each step attends the local Q block against the currently
held KV block while the next KV block is already in flight around the
ring — XLA overlaps the ppermute with the matmuls.

Two entry points:
* :func:`ring_attention_kernel` — per-device code, call inside an existing
  ``shard_map`` (what the model layer uses).
* :func:`ring_attention` — standalone wrapper that shard_maps the kernel
  over a mesh for direct use / testing.
"""

from __future__ import annotations

import functools

from ant_ray_tpu._private.jax_utils import import_jax


def _shard_map():
    from ant_ray_tpu._private.jax_utils import shard_map  # noqa: PLC0415

    return shard_map()


def ring_attention_kernel(q, k, v, *, axis_name: str, axis_size: int,
                          causal: bool = True, scale: float | None = None):
    """Exact ring attention for one device's shard.

    Args:
      q: (batch, q_len_local, num_heads, head_dim)
      k, v: (batch, kv_len_local, num_kv_heads, head_dim)
      axis_name: mesh axis the sequence is sharded over.
      axis_size: static size of that axis (number of ring stations).
      causal: apply causal masking using *global* positions.
      scale: softmax scale; default 1/sqrt(head_dim).

    Returns (batch, q_len_local, num_heads, head_dim), dtype of q.
    """
    jax = import_jax()
    import jax.numpy as jnp  # noqa: PLC0415
    from jax import lax  # noqa: PLC0415

    batch, q_len, num_heads, head_dim = q.shape
    kv_len = k.shape[1]
    num_kv_heads = k.shape[2]
    if num_heads % num_kv_heads != 0:
        raise ValueError(f"heads {num_heads} not divisible by kv heads "
                         f"{num_kv_heads}")
    groups = num_heads // num_kv_heads
    scale = scale if scale is not None else head_dim ** -0.5

    my_idx = lax.axis_index(axis_name)
    q_positions = my_idx * q_len + jnp.arange(q_len)          # global q pos

    q32 = q.astype(jnp.float32) * scale

    def attend_block(carry, step):
        o_acc, l_acc, m_acc, k_cur, v_cur = carry
        kv_block = (my_idx - step) % axis_size
        kv_positions = kv_block * kv_len + jnp.arange(kv_len)

        # scores: (batch, heads, q_len, kv_len)
        k_rep = jnp.repeat(k_cur.astype(jnp.float32), groups, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_rep)
        if causal:
            mask = kv_positions[None, :] > q_positions[:, None]
            scores = jnp.where(mask[None, None], -jnp.inf, scores)

        block_max = jnp.max(scores, axis=-1)                  # (b,h,q)
        m_new = jnp.maximum(m_acc, block_max)
        # All -inf rows (nothing attendable yet) stay neutral.
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        correction = jnp.where(
            jnp.isneginf(m_acc), 0.0, jnp.exp(m_acc - m_safe))

        v_rep = jnp.repeat(v_cur.astype(jnp.float32), groups, axis=2)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_rep)
        o_acc = o_acc * correction.transpose(0, 2, 1)[..., None] + pv
        l_acc = l_acc * correction + jnp.sum(p, axis=-1)

        # Rotate KV one station around the ring (overlapped by XLA with
        # the next step's compute).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, l_acc, m_new, k_next, v_next), None

    # Derive accumulators from q so they carry q's varying-axes type under
    # shard_map (plain zeros are "unvarying" and fail the scan carry check).
    o0 = jnp.zeros_like(q32)
    l0 = jnp.swapaxes(q32[..., 0] * 0.0, 1, 2)               # (b, h, q)
    m0 = l0 - jnp.inf
    (o, l, _m, _k, _v), _ = lax.scan(
        attend_block, (o0, l0, m0, k, v), jnp.arange(axis_size))

    l = jnp.where(l == 0.0, 1.0, l)                            # fully-masked rows
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis_name: str = "sp",
                   causal: bool = True, scale: float | None = None,
                   batch_axes=("dp", "fsdp"), head_axis: str | None = "tp"):
    """Standalone sharded ring attention over global arrays.

    q/k/v: (batch, seq, heads, head_dim) jax arrays (or numpy); sequence
    sharded over ``axis_name``, batch over ``batch_axes``, heads over
    ``head_axis``.
    """
    jax = import_jax()
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    axis_size = mesh.shape[axis_name]
    spec = P(batch_axes, axis_name, head_axis, None)
    kernel = functools.partial(
        ring_attention_kernel, axis_name=axis_name, axis_size=axis_size,
        causal=causal, scale=scale)
    fn = _shard_map()(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    return jax.jit(fn)(q, k, v)


def reference_attention(q, k, v, causal: bool = True,
                        scale: float | None = None):
    """Plain full attention (testing oracle for the parallel variants)."""
    import jax.numpy as jnp  # noqa: PLC0415

    batch, q_len, num_heads, head_dim = q.shape
    groups = num_heads // k.shape[2]
    scale = scale if scale is not None else head_dim ** -0.5
    k = jnp.repeat(k.astype(jnp.float32), groups, axis=2)
    v = jnp.repeat(v.astype(jnp.float32), groups, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k)
    if causal:
        q_pos = jnp.arange(q_len)
        mask = q_pos[None, :, None] < jnp.arange(k.shape[1])[None, None, :]
        scores = jnp.where(mask[:, None], -jnp.inf, scores)
    weights = jnp.exp(
        scores - jnp.max(scores, axis=-1, keepdims=True))
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
    return out.astype(q.dtype)
