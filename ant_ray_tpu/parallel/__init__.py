"""Parallelism layer: device meshes, sharding plans, and sequence-parallel
attention (ring / Ulysses) — the TPU-native expression of the reference's
parallelism strategies (SURVEY §2.3), plus the sequence/context parallelism
the reference lacks entirely."""

from ant_ray_tpu.parallel.mesh import (
    AxisNames,
    MeshConfig,
    build_mesh,
    local_chip_mesh,
)
from ant_ray_tpu.parallel.sharding import (
    LogicalAxisRules,
    DEFAULT_LLAMA_RULES,
    logical_to_spec,
    shard_pytree,
    constrain,
)
from ant_ray_tpu.parallel.ring import ring_attention
from ant_ray_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "AxisNames",
    "DEFAULT_LLAMA_RULES",
    "LogicalAxisRules",
    "MeshConfig",
    "build_mesh",
    "constrain",
    "local_chip_mesh",
    "logical_to_spec",
    "ring_attention",
    "shard_pytree",
    "ulysses_attention",
]
