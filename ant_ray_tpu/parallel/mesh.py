"""Device mesh construction for TPU slices.

The mesh is the foundation of every parallelism strategy (scaling-book
recipe: pick a mesh, annotate shardings, let XLA insert collectives).  Axis
order puts the bandwidth-hungriest axis innermost so it maps to the
tightest ICI neighborhood: ("pp", "dp", "fsdp", "ep", "sp", "tp").
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ant_ray_tpu._private.jax_utils import import_jax


class AxisNames:
    PIPELINE = "pp"
    DATA = "dp"
    FSDP = "fsdp"
    EXPERT = "ep"
    SEQUENCE = "sp"
    TENSOR = "tp"

    ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass
class MeshConfig:
    """Degrees for each parallelism axis; -1 on at most one axis means
    "absorb all remaining devices"."""

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def degrees(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AxisNames.ORDER}

    def resolve(self, n_devices: int) -> "MeshConfig":
        degrees = self.degrees()
        wildcards = [k for k, v in degrees.items() if v == -1]
        if len(wildcards) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in degrees.values() if v != -1)
        if wildcards:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            degrees[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {degrees} needs {fixed} devices, have {n_devices}")
        return MeshConfig(**degrees)


def build_mesh(config: MeshConfig | None = None, devices=None,
               **axis_degrees):
    """Build a jax Mesh with the standard axis order.

    ``build_mesh(dp=2, tp=4)`` or ``build_mesh(MeshConfig(fsdp=-1, tp=4))``.
    """
    jax = import_jax()
    from jax.sharding import Mesh  # noqa: PLC0415

    if config is None:
        config = MeshConfig(**axis_degrees)
    elif axis_degrees:
        raise ValueError("pass either MeshConfig or axis kwargs, not both")
    devices = list(devices) if devices is not None else list(jax.devices())
    config = config.resolve(len(devices))
    degrees = config.degrees()
    shape = tuple(degrees[name] for name in AxisNames.ORDER)
    array = np.array(devices).reshape(shape)
    return Mesh(array, AxisNames.ORDER)


def local_chip_mesh(**axis_degrees):
    """Mesh over this process's local devices only."""
    jax = import_jax()
    return build_mesh(devices=jax.local_devices(), **axis_degrees)


def mesh_axis_size(mesh, *names: str) -> int:
    return math.prod(mesh.shape[n] for n in names)
