"""Worker-group scaling policies
(ref: train/v2/_internal/execution/scaling_policy/ — the controller
asks the policy how large the next worker group should be at every
(re)start; FixedScalingPolicy demands the configured size, the elastic
policy fits the group to what the cluster can actually place).

Resize points match the reference: group start and group restart after
a failure.  A mid-run resize is a group restart — training resumes from
the latest checkpoint, which is exactly the failure-recovery path, so
elasticity reuses it rather than inventing a second lifecycle.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)


class ScalingPolicy:
    """Decides the world size for the next worker-group launch."""

    def workers_for_attempt(self, scaling, available: dict,
                            total: dict, attempt: int = 0) -> int:
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured size (ref: FixedScalingPolicy)."""

    def workers_for_attempt(self, scaling, available, total,
                            attempt: int = 0) -> int:
        return scaling.num_workers


class ElasticScalingPolicy(ScalingPolicy):
    """Fit the group to current capacity within [min_workers,
    num_workers] (ref: the elastic scaling decision — size the next
    group by how many rank bundles the cluster can place now).

    A shrunken cluster yields a smaller world; when capacity returns,
    the next (re)start grows back toward num_workers.
    """

    def __init__(self, min_workers: int):
        if min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        self.min_workers = min_workers
        # Upper bound learned from reservation failures: aggregate
        # capacity can over-estimate what is PLACEABLE (per-node
        # fragmentation), so an unplaceable gang steps the next request
        # down instead of burning every attempt at the same size.
        self._cap: int | None = None

    def _placeable(self, scaling, resources: dict) -> int:
        demand = scaling.worker_resources()
        counts = []
        for key, per_worker in demand.items():
            if per_worker <= 0:
                continue
            counts.append(int(resources.get(key, 0.0) // per_worker))
        return min(counts) if counts else scaling.num_workers

    def workers_for_attempt(self, scaling, available, total,
                            attempt: int = 0) -> int:
        # First attempt sizes by TOTAL capacity (the group's own PG
        # frees its bundles between attempts; transient consumers
        # shouldn't shrink the world permanently).  Retries also
        # consult the AVAILABLE view: if reservations keep failing, a
        # co-tenant is holding capacity for real, and re-requesting the
        # total-derived size would burn every failure attempt on an
        # unplaceable gang.
        fit = self._placeable(scaling, total)
        if attempt > 0:
            avail_fit = self._placeable(scaling, available)
            fit = min(fit, max(self.min_workers, avail_fit))
        if self._cap is not None:
            fit = min(fit, self._cap)
        world = max(self.min_workers, min(scaling.num_workers, fit))
        if world < scaling.num_workers:
            logger.warning(
                "elastic: cluster fits %d/%d workers — launching a "
                "reduced group", world, scaling.num_workers)
        return world

    def note_unplaceable(self, world: int) -> None:
        """A gang of ``world`` bundles timed out: step down next time."""
        self._cap = max(self.min_workers, world - 1)

    def note_group_started(self) -> None:
        """A group launched: forget the learned cap (capacity may have
        returned; the next restart probes upward again)."""
        self._cap = None


def policy_for(scaling) -> ScalingPolicy:
    if getattr(scaling, "min_workers", 0):
        if scaling.use_tpu and scaling.topology:
            raise ValueError(
                "elastic scaling (min_workers) cannot be combined with a "
                "whole-slice topology reservation — a slice's ICI mesh "
                "has a fixed host count")
        return ElasticScalingPolicy(scaling.min_workers)
    return FixedScalingPolicy()
