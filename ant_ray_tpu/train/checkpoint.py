"""Checkpoints: directory-backed handles + orbax pytree helpers + a top-K
retention manager (ref: train/v2/_internal/execution/checkpoint/ +
storage.py; orbax replaces torch.save as the native TPU path)."""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass


@dataclass(frozen=True)
class Checkpoint:
    """A handle to a checkpoint directory (ref: ray.train.Checkpoint)."""

    path: str

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=os.path.abspath(path))

    def as_directory(self) -> str:
        return self.path

    # ---- jax pytree convenience (orbax)

    @classmethod
    def from_pytree(cls, tree, path: str) -> "Checkpoint":
        save_pytree(tree, path)
        return cls(path=os.path.abspath(path))

    def to_pytree(self, abstract_tree=None):
        return load_pytree(self.path, abstract_tree)


def save_pytree(tree, path: str) -> None:
    import orbax.checkpoint as ocp  # noqa: PLC0415

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, tree)


def load_pytree(path: str, abstract_tree=None):
    import orbax.checkpoint as ocp  # noqa: PLC0415

    with ocp.PyTreeCheckpointer() as ckptr:
        if abstract_tree is not None:
            return ckptr.restore(os.path.abspath(path),
                                 args=ocp.args.PyTreeRestore(abstract_tree))
        return ckptr.restore(os.path.abspath(path))


class CheckpointManager:
    """Controller-side retention of reported checkpoints (top-K by
    recency; ref: CheckpointManager keeps top-K)."""

    # Per-fit token file: <storage>/.run_token names the CURRENT fit;
    # each registered checkpoint carries a copy inside its dir.  A
    # controller-death restore adopts ONLY token-matching checkpoints,
    # so a previous same-named run's leftovers are never resumed from —
    # and nothing is ever deleted up front (a relaunch that crashes
    # before its first checkpoint must not have destroyed the old ones).
    _TOKEN_FILE = ".run_token"

    def __init__(self, storage_path: str, num_to_keep: int | None = None,
                 restore: bool = False, run_token: str | None = None):
        import uuid  # noqa: PLC0415

        self._storage_path = storage_path
        self._num_to_keep = num_to_keep
        self._checkpoints: list[Checkpoint] = []
        os.makedirs(storage_path, exist_ok=True)
        token_path = os.path.join(storage_path, self._TOKEN_FILE)
        # The fit's token comes from the TRAINER when it drives the
        # controller (one token for every incarnation of one fit, so a
        # pre-first-checkpoint controller death can't resurrect a
        # previous run's token); standalone use generates one.
        self._token = run_token or ""
        if restore:
            if not self._token:
                try:
                    with open(token_path) as f:
                        self._token = f.read().strip()
                except OSError:
                    self._token = ""
            # Restore — OPT-IN (a recreated controller after controller
            # death): adopt this fit's checkpoints, identified by token.
            for name in sorted(os.listdir(storage_path)):
                path = os.path.join(storage_path, name)
                if name.startswith("checkpoint_") and os.path.isdir(path):
                    try:
                        int(name.rsplit("_", 1)[1])
                    except (ValueError, IndexError):
                        continue
                    if self._token and self._read_token(path) == \
                            self._token:
                        self._checkpoints.append(
                            Checkpoint.from_directory(path))
        else:
            self._token = self._token or uuid.uuid4().hex
            with open(token_path, "w") as f:
                f.write(self._token)

    @classmethod
    def _read_token(cls, checkpoint_dir: str) -> str | None:
        try:
            with open(os.path.join(checkpoint_dir, cls._TOKEN_FILE)) as f:
                return f.read().strip()
        except OSError:
            return None

    @property
    def latest(self) -> Checkpoint | None:
        return self._checkpoints[-1] if self._checkpoints else None

    @property
    def next_index(self) -> int:
        """First unused checkpoint index: highest existing index ON
        DISK + 1 (adopted or not — a restore that declines foreign
        dirs must not start overwriting them either), monotonic across
        controller incarnations."""
        best = -1
        try:
            for name in os.listdir(self._storage_path):
                if name.startswith("checkpoint_"):
                    try:
                        best = max(best, int(name.rsplit("_", 1)[1]))
                    except (ValueError, IndexError):
                        continue
        except OSError:
            pass
        return best + 1

    def register(self, checkpoint: Checkpoint) -> None:
        try:
            # Stamp the fit's token into the dir (see _TOKEN_FILE note).
            with open(os.path.join(checkpoint.path,
                                   self._TOKEN_FILE), "w") as f:
                f.write(self._token)
        except OSError as e:
            # An unstamped checkpoint is invisible to a controller-
            # death restore — losable progress deserves a breadcrumb.
            import logging  # noqa: PLC0415

            logging.getLogger(__name__).warning(
                "could not stamp run token into %s (%s); this "
                "checkpoint will not be adopted by a restore",
                checkpoint.path, e)
        self._checkpoints.append(checkpoint)
        if self._num_to_keep is not None:
            # Normalized containment check: checkpoint paths are
            # abspathed, so a relative storage_path would never prefix-
            # match (silently disabling retention), and a bare prefix
            # without the trailing separator could cross sibling dirs
            # ("/a/exp10" startswith "/a/exp1").
            root = os.path.abspath(self._storage_path) + os.sep
            while len(self._checkpoints) > self._num_to_keep:
                stale = self._checkpoints.pop(0)
                if stale.path.startswith(root):
                    shutil.rmtree(stale.path, ignore_errors=True)

    def next_checkpoint_dir(self, index: int) -> str:
        return os.path.join(self._storage_path, f"checkpoint_{index:06d}")
