"""Checkpoints: directory-backed handles + orbax pytree helpers + a top-K
retention manager (ref: train/v2/_internal/execution/checkpoint/ +
storage.py; orbax replaces torch.save as the native TPU path)."""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass


@dataclass(frozen=True)
class Checkpoint:
    """A handle to a checkpoint directory (ref: ray.train.Checkpoint)."""

    path: str

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=os.path.abspath(path))

    def as_directory(self) -> str:
        return self.path

    # ---- jax pytree convenience (orbax)

    @classmethod
    def from_pytree(cls, tree, path: str) -> "Checkpoint":
        save_pytree(tree, path)
        return cls(path=os.path.abspath(path))

    def to_pytree(self, abstract_tree=None):
        return load_pytree(self.path, abstract_tree)


def save_pytree(tree, path: str) -> None:
    import orbax.checkpoint as ocp  # noqa: PLC0415

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, tree)


def load_pytree(path: str, abstract_tree=None):
    import orbax.checkpoint as ocp  # noqa: PLC0415

    with ocp.PyTreeCheckpointer() as ckptr:
        if abstract_tree is not None:
            return ckptr.restore(os.path.abspath(path),
                                 args=ocp.args.PyTreeRestore(abstract_tree))
        return ckptr.restore(os.path.abspath(path))


class CheckpointManager:
    """Controller-side retention of reported checkpoints (top-K by
    recency; ref: CheckpointManager keeps top-K)."""

    def __init__(self, storage_path: str, num_to_keep: int | None = None,
                 restore: bool = False):
        self._storage_path = storage_path
        self._num_to_keep = num_to_keep
        self._checkpoints: list[Checkpoint] = []
        os.makedirs(storage_path, exist_ok=True)
        if restore:
            # Restore from disk — OPT-IN (a recreated controller after
            # controller death).  Safe to adopt everything present
            # because the fresh incarnation below cleared the dir, so
            # whatever exists was written by THIS fit.
            for name in sorted(os.listdir(storage_path)):
                path = os.path.join(storage_path, name)
                if name.startswith("checkpoint_") and os.path.isdir(path):
                    try:
                        int(name.rsplit("_", 1)[1])
                    except (ValueError, IndexError):
                        continue
                    self._checkpoints.append(
                        Checkpoint.from_directory(path))
        else:
            # Fresh run: the storage path belongs to this run — clear
            # leftover checkpoint dirs from a previous same-named run
            # so (a) this run never half-overwrites a stale series and
            # (b) a later controller-death restore can't adopt a
            # foreign run's weights.  (Anonymous runs get unique names,
            # so this only affects deliberate name reuse, which already
            # overwrote checkpoints progressively.)
            for name in os.listdir(storage_path):
                path = os.path.join(storage_path, name)
                if name.startswith("checkpoint_") and os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)

    @property
    def latest(self) -> Checkpoint | None:
        return self._checkpoints[-1] if self._checkpoints else None

    @property
    def next_index(self) -> int:
        """First unused checkpoint index (monotonic across controller
        incarnations — derived from the highest on-disk index, not the
        in-memory count, which retention prunes)."""
        if not self._checkpoints:
            return 0
        tail = os.path.basename(self._checkpoints[-1].path)
        try:
            return int(tail.rsplit("_", 1)[1]) + 1
        except (ValueError, IndexError):
            return len(self._checkpoints)

    def register(self, checkpoint: Checkpoint) -> None:
        self._checkpoints.append(checkpoint)
        if self._num_to_keep is not None:
            while len(self._checkpoints) > self._num_to_keep:
                stale = self._checkpoints.pop(0)
                if stale.path.startswith(self._storage_path):
                    shutil.rmtree(stale.path, ignore_errors=True)

    def next_checkpoint_dir(self, index: int) -> str:
        return os.path.join(self._storage_path, f"checkpoint_{index:06d}")
