"""Checkpoints: directory-backed handles + orbax pytree helpers + a top-K
retention manager (ref: train/v2/_internal/execution/checkpoint/ +
storage.py; orbax replaces torch.save as the native TPU path)."""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass


@dataclass(frozen=True)
class Checkpoint:
    """A handle to a checkpoint directory (ref: ray.train.Checkpoint)."""

    path: str

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=os.path.abspath(path))

    def as_directory(self) -> str:
        return self.path

    # ---- jax pytree convenience (orbax)

    @classmethod
    def from_pytree(cls, tree, path: str) -> "Checkpoint":
        save_pytree(tree, path)
        return cls(path=os.path.abspath(path))

    def to_pytree(self, abstract_tree=None):
        return load_pytree(self.path, abstract_tree)


def save_pytree(tree, path: str) -> None:
    import orbax.checkpoint as ocp  # noqa: PLC0415

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, tree)


def load_pytree(path: str, abstract_tree=None):
    import orbax.checkpoint as ocp  # noqa: PLC0415

    with ocp.PyTreeCheckpointer() as ckptr:
        if abstract_tree is not None:
            return ckptr.restore(os.path.abspath(path),
                                 args=ocp.args.PyTreeRestore(abstract_tree))
        return ckptr.restore(os.path.abspath(path))


class CheckpointManager:
    """Controller-side retention of reported checkpoints (top-K by
    recency; ref: CheckpointManager keeps top-K)."""

    def __init__(self, storage_path: str, num_to_keep: int | None = None):
        self._storage_path = storage_path
        self._num_to_keep = num_to_keep
        self._checkpoints: list[Checkpoint] = []
        os.makedirs(storage_path, exist_ok=True)

    @property
    def latest(self) -> Checkpoint | None:
        return self._checkpoints[-1] if self._checkpoints else None

    def register(self, checkpoint: Checkpoint) -> None:
        self._checkpoints.append(checkpoint)
        if self._num_to_keep is not None:
            while len(self._checkpoints) > self._num_to_keep:
                stale = self._checkpoints.pop(0)
                if stale.path.startswith(self._storage_path):
                    shutil.rmtree(stale.path, ignore_errors=True)

    def next_checkpoint_dir(self, index: int) -> str:
        return os.path.join(self._storage_path, f"checkpoint_{index:06d}")
