"""Checkpoints: directory-backed handles + orbax pytree helpers + a top-K
retention manager (ref: train/v2/_internal/execution/checkpoint/ +
storage.py; orbax replaces torch.save as the native TPU path)."""

from __future__ import annotations

import io
import logging
import os
import shutil
import tarfile
import tempfile
import uuid
from dataclasses import dataclass

logger = logging.getLogger(__name__)

# Non-final artifacts of the atomic-save / replica-materialize dance;
# restore scans MUST ignore them (and may sweep stale ones).
_TMP_MARKERS = (".tmp-", ".old-")


@dataclass(frozen=True)
class Checkpoint:
    """A handle to a checkpoint directory (ref: ray.train.Checkpoint).

    ``replica``: optional ObjectRef of the packed directory in the
    in-cluster object store (CheckpointConfig.replicate).  When the
    directory path is not visible from the reading process's node (no
    shared storage), ``as_directory``/``to_pytree`` materialize the
    checkpoint from the replica — pulled over the bulk transfer
    channel at object-plane bandwidth."""

    path: str
    replica: "object | None" = None

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=os.path.abspath(path))

    def with_replica(self, ref) -> "Checkpoint":
        return Checkpoint(path=self.path, replica=ref)

    def as_directory(self) -> str:
        if os.path.isdir(self.path) or self.replica is None:
            return self.path
        return self._materialize_replica()

    def _materialize_replica(self) -> str:
        """Unpack the object-store replica into a node-local cache dir
        (shared by colocated readers).  Keyed by the replica ref's
        object id, not just the checkpoint path — a later fit reusing
        the same storage_path/name/index must never restore a previous
        run's weights from a stale cache entry."""
        import ant_ray_tpu as art  # noqa: PLC0415

        dest = os.path.join(
            tempfile.gettempdir(), "art_ckpt_replicas",
            f"{self.path.strip(os.sep).replace(os.sep, '_')}"
            f"-{self.replica.hex()[:16]}")
        if os.path.isdir(dest):
            return dest
        data = art.get(self.replica)
        unpack_checkpoint(data, dest)
        logger.info("materialized checkpoint replica for %s (%d bytes)",
                    self.path, len(data))
        return dest

    # ---- jax pytree convenience (orbax)

    @classmethod
    def from_pytree(cls, tree, path: str) -> "Checkpoint":
        save_pytree(tree, path)
        return cls(path=os.path.abspath(path))

    def to_pytree(self, abstract_tree=None):
        return load_pytree(self.as_directory(), abstract_tree)


def save_pytree(tree, path: str) -> None:
    """Atomic orbax save: write to a ``.tmp-`` sibling, then rename
    into place — a crash mid-save can never destroy the previous
    checkpoint at ``path`` (the old destroy-then-save order lost it),
    and a torn write is never visible under the final name.  Restore
    scans ignore ``.tmp-``/``.old-`` leftovers."""
    import orbax.checkpoint as ocp  # noqa: PLC0415

    path = os.path.abspath(path)
    nonce = uuid.uuid4().hex[:8]
    tmp = f"{path}.tmp-{nonce}"
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp, tree)
        if os.path.exists(path):
            # Two renames, no window where neither copy exists: the old
            # dir steps aside (ignored by restores), the complete new
            # one takes the name, then the old is reaped.
            old = f"{path}.old-{nonce}"
            os.rename(path, old)
            os.rename(tmp, path)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def pack_checkpoint_dir(path: str) -> bytes:
    """Checkpoint directory -> one replicable blob (tar, uncompressed —
    checkpoints are mostly incompressible array bytes and the object
    plane moves them at wire speed)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        tar.add(path, arcname=".")
    return buf.getvalue()


def unpack_checkpoint(data: bytes, dest: str) -> str:
    """Atomically materialize a packed checkpoint at ``dest`` (unpack
    into a tmp sibling, rename; a concurrent reader either sees the
    complete directory or none)."""
    dest = os.path.abspath(dest)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = f"{dest}.tmp-{uuid.uuid4().hex[:8]}"
    try:
        with tarfile.open(fileobj=io.BytesIO(data)) as tar:
            try:
                tar.extractall(tmp, filter="data")
            except TypeError:       # pre-3.12 tarfile: no filter arg
                tar.extractall(tmp)  # noqa: S202 — self-produced blob
        try:
            os.rename(tmp, dest)
        except OSError:
            if not os.path.isdir(dest):   # lost a race to a peer: fine
                raise
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def _adopt_orphaned_old(path: str) -> None:
    """Close save_pytree's two-rename crash window: a kill between
    `rename(path, old)` and `rename(tmp, path)` leaves the complete
    previous checkpoint ONLY under the ``.old-`` name — adopt it back
    so the acked steps it represents are not lost."""
    if os.path.exists(path):
        return
    import glob as _glob  # noqa: PLC0415

    orphans = sorted(_glob.glob(path + ".old-*"), key=os.path.getmtime)
    if orphans:
        try:
            os.rename(orphans[-1], path)
            logger.warning("adopted orphaned checkpoint %s -> %s "
                           "(crash mid-swap)", orphans[-1], path)
        except OSError:   # lost a race to a concurrent adopter: fine
            pass


def load_pytree(path: str, abstract_tree=None):
    import orbax.checkpoint as ocp  # noqa: PLC0415

    _adopt_orphaned_old(os.path.abspath(path))
    with ocp.PyTreeCheckpointer() as ckptr:
        if abstract_tree is not None:
            return ckptr.restore(os.path.abspath(path),
                                 args=ocp.args.PyTreeRestore(abstract_tree))
        return ckptr.restore(os.path.abspath(path))


class CheckpointManager:
    """Controller-side retention of reported checkpoints (top-K by
    recency; ref: CheckpointManager keeps top-K)."""

    # Per-fit token file: <storage>/.run_token names the CURRENT fit;
    # each registered checkpoint carries a copy inside its dir.  A
    # controller-death restore adopts ONLY token-matching checkpoints,
    # so a previous same-named run's leftovers are never resumed from —
    # and nothing is ever deleted up front (a relaunch that crashes
    # before its first checkpoint must not have destroyed the old ones).
    _TOKEN_FILE = ".run_token"

    def __init__(self, storage_path: str, num_to_keep: int | None = None,
                 restore: bool = False, run_token: str | None = None):
        import uuid  # noqa: PLC0415

        self._storage_path = storage_path
        self._num_to_keep = num_to_keep
        self._checkpoints: list[Checkpoint] = []
        os.makedirs(storage_path, exist_ok=True)
        token_path = os.path.join(storage_path, self._TOKEN_FILE)
        # The fit's token comes from the TRAINER when it drives the
        # controller (one token for every incarnation of one fit, so a
        # pre-first-checkpoint controller death can't resurrect a
        # previous run's token); standalone use generates one.
        self._token = run_token or ""
        if restore:
            if not self._token:
                try:
                    with open(token_path) as f:
                        self._token = f.read().strip()
                except OSError:
                    self._token = ""
            # A crash inside save_pytree's two-rename swap can leave
            # the newest complete checkpoint only under its .old- name
            # — rescue those before scanning (see _adopt_orphaned_old).
            for name in os.listdir(storage_path):
                base, sep, _rest = name.partition(".old-")
                if sep and base.startswith("checkpoint_"):
                    _adopt_orphaned_old(os.path.join(storage_path, base))
            # Restore — OPT-IN (a recreated controller after controller
            # death): adopt this fit's checkpoints, identified by token.
            for name in sorted(os.listdir(storage_path)):
                path = os.path.join(storage_path, name)
                if name.startswith("checkpoint_") and os.path.isdir(path):
                    try:
                        int(name.rsplit("_", 1)[1])
                    except (ValueError, IndexError):
                        continue
                    if self._token and self._read_token(path) == \
                            self._token:
                        self._checkpoints.append(
                            Checkpoint.from_directory(path))
        else:
            self._token = self._token or uuid.uuid4().hex
            with open(token_path, "w") as f:
                f.write(self._token)

    @classmethod
    def _read_token(cls, checkpoint_dir: str) -> str | None:
        try:
            with open(os.path.join(checkpoint_dir, cls._TOKEN_FILE)) as f:
                return f.read().strip()
        except OSError:
            return None

    @property
    def latest(self) -> Checkpoint | None:
        return self._checkpoints[-1] if self._checkpoints else None

    @property
    def next_index(self) -> int:
        """First unused checkpoint index: highest existing index ON
        DISK + 1 (adopted or not — a restore that declines foreign
        dirs must not start overwriting them either), monotonic across
        controller incarnations."""
        best = -1
        try:
            for name in os.listdir(self._storage_path):
                if name.startswith("checkpoint_"):
                    try:
                        best = max(best, int(name.rsplit("_", 1)[1]))
                    except (ValueError, IndexError):
                        continue
        except OSError:
            pass
        return best + 1

    def register(self, checkpoint: Checkpoint) -> None:
        try:
            # Stamp the fit's token into the dir (see _TOKEN_FILE note).
            with open(os.path.join(checkpoint.path,
                                   self._TOKEN_FILE), "w") as f:
                f.write(self._token)
        except OSError as e:
            # An unstamped checkpoint is invisible to a controller-
            # death restore — losable progress deserves a breadcrumb.
            import logging  # noqa: PLC0415

            logging.getLogger(__name__).warning(
                "could not stamp run token into %s (%s); this "
                "checkpoint will not be adopted by a restore",
                checkpoint.path, e)
        # Only the LATEST checkpoint keeps an object-store replica:
        # dropping older entries' refs frees their packed blobs, so a
        # keep-all run doesn't pin every checkpoint in store memory
        # (recovery only ever restores the newest).
        for i, stale in enumerate(self._checkpoints):
            if getattr(stale, "replica", None) is not None:
                self._checkpoints[i] = Checkpoint(path=stale.path)
        self._checkpoints.append(checkpoint)
        if self._num_to_keep is not None:
            # Normalized containment check: checkpoint paths are
            # abspathed, so a relative storage_path would never prefix-
            # match (silently disabling retention), and a bare prefix
            # without the trailing separator could cross sibling dirs
            # ("/a/exp10" startswith "/a/exp1").
            root = os.path.abspath(self._storage_path) + os.sep
            while len(self._checkpoints) > self._num_to_keep:
                stale = self._checkpoints.pop(0)
                if stale.path.startswith(root):
                    shutil.rmtree(stale.path, ignore_errors=True)

    def next_checkpoint_dir(self, index: int) -> str:
        return os.path.join(self._storage_path, f"checkpoint_{index:06d}")
