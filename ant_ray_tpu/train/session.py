"""In-worker training session: report() / get_context() / get_checkpoint()
(ref: train/v2/_internal/execution/train_fn_utils.py + session semantics)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    storage_path: str = ""
    controller: Any = None              # ActorHandle of the controller
    latest_checkpoint: Any = None
    # Group-restart counter (0 on the first launch): lets user loops
    # derive attempt-unique rendezvous names so a restarted gang never
    # collides with its predecessor's collective group.
    attempt: int = 0
    # Whether this rank binds TPU chips (picks the collective backend
    # for sync_gradients: xla on TPU gangs, gloo on CPU gangs).
    use_tpu: bool = False
    # Rank→slice partition of the gang (collective.types.SliceTopology)
    # when the job spans multiple accelerator slices; sync_gradients
    # routes through the hierarchical intra-slice (ICI) / inter-slice
    # (DCN) allreduce when set.
    slice_topology: Any = None
    # name -> DataIterator for this rank (from the trainer's datasets=).
    dataset_shards: dict = field(default_factory=dict)
    # The loop's StepProfiler (observability/step_profiler.py) — it
    # registers itself here on construction, and report() auto-attaches
    # its latest step record for the controller's cross-rank gauges.
    step_profiler: Any = None
    _report_lock: threading.Lock = field(default_factory=threading.Lock)


class PreemptionInterrupt(BaseException):
    """Raised inside a train loop by :func:`report` when the controller
    has ordered a proactive drain stop (a node hosting the gang got a
    preemption/maintenance notice).  The checkpoint carried by that
    very report was already registered, so unwinding here loses zero
    steps — the controller relaunches the gang off the draining node
    and resumes from it.

    Derives from ``BaseException`` so a user loop's broad
    ``except Exception`` cannot swallow the drain; the worker shim
    (TrainWorker.run) catches it."""


_ctx = threading.local()


def _set_context(ctx: TrainContext) -> None:
    _ctx.value = ctx


def get_context() -> TrainContext:
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        raise RuntimeError(
            "No training context: this API is only available inside a "
            "train_loop_per_worker")
    return ctx


def report(metrics: dict, checkpoint=None) -> None:
    """Report metrics (and optionally a checkpoint) to the controller
    (ref: ray.train.report).  Blocks until the controller acknowledged, so
    checkpoint ordering is deterministic.

    When the loop runs a :class:`~ant_ray_tpu.observability.StepProfiler`,
    the latest step record rides along (``_step_record``) — the
    controller folds every rank's records into step-time and
    rank-skew gauges, and the profiler's publish buffer is flushed so
    the timeline's device rows stay current."""
    import ant_ray_tpu as art  # noqa: PLC0415

    ctx = get_context()
    metrics = dict(metrics)
    prof = ctx.step_profiler
    if prof is not None and "_step_record" not in metrics:
        last = prof.last
        if last is not None:
            metrics["_step_record"] = last.as_dict()
        prof.flush()
    with ctx._report_lock:
        reply = art.get(ctx.controller.report_from_worker.remote(
            ctx.world_rank, metrics, checkpoint))
    # The ack doubles as the drain channel: when the controller has a
    # preemption notice for this gang's node(s), it replies stop=True —
    # the checkpoint this report carried is already registered, so
    # unwinding NOW is the zero-step-loss exit point.
    if isinstance(reply, dict) and reply.get("stop"):
        raise PreemptionInterrupt


def get_dataset_shard(name: str = "train", device_feed: dict | None = None):
    """This rank's streaming DataIterator for the trainer's
    ``datasets={name: ds}`` (ref: train/_internal/session.py:1134).
    Split datasets are coordinated streaming shards (one pass of the
    plan per epoch, shared across ranks); broadcast datasets return a
    full-dataset iterator.

    The shard exposes ``iter_device_batches(...)`` — prefetched,
    double-buffered host→HBM batch delivery (data/device_feed.py) —
    preconfigured from ``DataConfig.device_feed`` by the controller.
    ``device_feed`` here overlays extra defaults from inside the loop
    (e.g. a sharding built on this worker's mesh)."""
    ctx = get_context()
    shard = ctx.dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset {name!r} was passed to the trainer "
            f"(have: {sorted(ctx.dataset_shards)})")
    if device_feed:
        shard.configure_device_feed(**device_feed)
    return shard


def sync_gradients(grads, op=None, *, group_name: str | None = None,
                   **fusion_knobs):
    """Data-parallel gradient sync over the worker gang — fused
    bucketed allreduce by default (util/collective/fusion.py): the
    gradient pytree packs into 4 MiB flat buckets, one collective per
    bucket, bucket k+1's transfer pipelined against bucket k's
    collective.  Defaults to AVERAGE over ranks.

    The gang's collective group is created lazily on first call
    (attempt-unique name, so a restarted gang never collides with its
    predecessor's) — xla backend on TPU gangs, gloo on CPU gangs.
    ``fusion_knobs`` forward to ``collective.sync_pytree``
    (``bucket_bytes``, ``transport_dtype``, ``overlap``,
    ``hierarchy``); when the gang spans multiple slices
    (``ScalingConfig.num_slices`` / TPU pod labels), the context's
    slice topology is the default hierarchy.  World size 1 returns the
    pytree unchanged."""
    ctx = get_context()
    if ctx.world_size <= 1:
        return grads

    from ant_ray_tpu.util import collective as col  # noqa: PLC0415
    from ant_ray_tpu.util.collective import ReduceOp  # noqa: PLC0415

    group = _ensure_gang_group(ctx, group_name)
    fusion_knobs.setdefault("hierarchy", ctx.slice_topology)
    return col.sync_pytree(grads, group_name=group,
                           op=ReduceOp.AVERAGE if op is None else op,
                           **fusion_knobs)


def _ensure_gang_group(ctx: TrainContext,
                       group_name: "str | None" = None) -> str:
    """Lazily create this gang's collective group (shared by
    sync_gradients and gradient_syncer) and wire its fusion stats into
    the step profiler."""
    from ant_ray_tpu.util import collective as col  # noqa: PLC0415

    group = group_name or (
        f"train-sync-{ctx.experiment_name or 'run'}-a{ctx.attempt}")
    if not col.is_group_initialized(group):
        col.init_collective_group(
            ctx.world_size, ctx.world_rank,
            backend="xla" if ctx.use_tpu else "gloo", group_name=group)
        if ctx.step_profiler is not None:
            # The gang's fusion stats become the profiler's collective/
            # h2d phases — one attach per group lifetime (deltas).
            try:
                ctx.step_profiler.attach_fusion_stats(group)
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass
    return group


def gradient_syncer(op=None, *, group_name: str | None = None,
                    **fusion_knobs):
    """Ready-hook gradient sync for overlapping communication with the
    backward pass (util/collective/fusion.py GradientSyncer): leaves
    are assigned to buckets in reverse-topological order, and each
    bucket's collective launches the moment its last leaf
    materializes — call ``begin(template)`` once per step,
    ``ready(i, grad)`` as each leaf's gradient lands, and ``wait()``
    for the averaged pytree.  ``sync_gradients`` is the one-shot
    degenerate form.  Returns None at world size 1 (nothing to sync —
    callers fall back to their local gradients)."""
    ctx = get_context()
    if ctx.world_size <= 1:
        return None

    from ant_ray_tpu.util import collective as col  # noqa: PLC0415
    from ant_ray_tpu.util.collective import ReduceOp  # noqa: PLC0415

    group = _ensure_gang_group(ctx, group_name)
    fusion_knobs.setdefault("hierarchy", ctx.slice_topology)
    return col.gradient_syncer(
        group, op=ReduceOp.AVERAGE if op is None else op,
        **fusion_knobs)


def get_checkpoint():
    """Latest checkpoint to resume from (set on restore/restart)."""
    return get_context().latest_checkpoint


def get_world_rank() -> int:
    return get_context().world_rank


def get_world_size() -> int:
    return get_context().world_size
