"""JaxTrainer — the user-facing distributed trainer
(ref: train/v2/jax/jax_trainer.py:19 + api/data_parallel_trainer.py:155).
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from ant_ray_tpu.train.config import Result, RunConfig, ScalingConfig

logger = logging.getLogger(__name__)


class JaxTrainer:
    """Distributed training driver: one actor per worker (per TPU host in
    a slice), rendezvous, metric/checkpoint reporting, elastic restarts.

    Example::

        def train_loop(config):
            ctx = train.get_context()
            for step in range(config["steps"]):
                ...
                train.report({"loss": loss}, checkpoint=params)

        trainer = JaxTrainer(
            train_loop,
            train_loop_config={"steps": 100},
            scaling_config=ScalingConfig(num_workers=4, use_tpu=True,
                                         topology="4x8"),
        )
        result = trainer.fit()
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 dataset_config=None):
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        # datasets={"train": ds}: each worker pulls its coordinated
        # streaming shard via train.get_dataset_shard("train") (ref:
        # api/data_parallel_trainer.py:83, datasets= + DataConfig).
        self._datasets = datasets or {}
        self._dataset_config = dataset_config
        if not self._run_config.name:
            # Anonymous runs get a per-trainer unique name: two
            # concurrent fits in one job must not share a PG name (the
            # leaked-group cleanup would remove the healthy run's
            # reservation) or a checkpoint dir (a fresh run would
            # clobber the previous anonymous run's checkpoints).
            import dataclasses as _dc  # noqa: PLC0415
            import uuid as _uuid  # noqa: PLC0415

            self._run_config = _dc.replace(
                self._run_config, name=f"run-{_uuid.uuid4().hex[:6]}")

    def fit(self) -> Result:
        import ant_ray_tpu as art  # noqa: PLC0415
        from ant_ray_tpu.train.controller import TrainController  # noqa: PLC0415

        if not art.is_initialized():
            art.init()
        # Soft-pin the controller to the driver's node: the controller
        # must survive worker-node loss to run the elastic restart, and
        # the driver's node is the head for every local flow — an
        # owned cluster's node_address IS the spawned head, and a
        # connecting driver gets the first-registered (head) node from
        # services.find_local_node.  The pin is SOFT (falls back to
        # DEFAULT if that node is gone) and the controller-death retry
        # below covers the residual mis-pin cases (e.g. a head that
        # re-registered after a restart).  Ref: the reference runs its
        # TrainController where the driver entrypoint lives.
        strategy = None
        try:
            from ant_ray_tpu.api import global_worker  # noqa: PLC0415
            from ant_ray_tpu.util.scheduling_strategies import (  # noqa: PLC0415
                NodeAffinitySchedulingStrategy,
            )

            runtime = global_worker.runtime
            my_address = getattr(runtime, "node_address", None)
            if my_address:
                node_id = next(
                    (n["NodeID"] for n in art.nodes()
                     if n["Alive"] and n["Address"] == my_address), None)
                if node_id is not None:
                    strategy = NodeAffinitySchedulingStrategy(
                        node_id, soft=True)
        except Exception as e:  # noqa: BLE001 — cluster state probe
            logger.warning("controller node pin unavailable (%s); "
                           "using DEFAULT placement", e)
        controller_cls = art.remote(TrainController).options(
            max_concurrency=8, num_cpus=0, scheduling_strategy=strategy)
        # The controller itself can die with a node (the soft pin only
        # covers owned-cluster drivers) — recreate it up to
        # max_failures times; run() resumes from the latest persisted
        # checkpoint, so a controller loss costs the current interval,
        # not the run (ref: Trainer.restore semantics).
        from ant_ray_tpu.exceptions import ActorDiedError  # noqa: PLC0415

        retries = max(
            0, self._run_config.failure_config.max_controller_failures)
        # The trainer OWNS the fit's checkpoint run-token: a retry then
        # adopts only checkpoints this fit stamped, even if an earlier
        # controller died before writing any token (a stale .run_token
        # from a previous same-named run can never match).
        import uuid as _uuid  # noqa: PLC0415

        run_token = _uuid.uuid4().hex
        for attempt in range(retries + 1):
            controller = controller_cls.remote(
                self._loop, self._loop_config, self._scaling,
                self._run_config, attempt > 0, run_token,
                self._datasets, self._dataset_config)
            try:
                result: Result = art.get(
                    controller.run.remote(controller), timeout=None)
                break
            except ActorDiedError:
                if attempt == retries:
                    # Final failure still must not leak the gang: the
                    # dead controller never ran its PG release, and the
                    # PG removal also kills the orphaned workers.
                    self._release_leaked_groups(art)
                    self._kill_leaked_workers(art)
                    raise
                logger.warning(
                    "train controller died (attempt %d/%d); recreating "
                    "— resumes from the latest checkpoint IN "
                    "storage_path (%s); node-local paths restart from "
                    "scratch after node loss",
                    attempt + 1, retries + 1,
                    self._run_config.resolved_storage_path())
                self._release_leaked_groups(art)
                self._kill_leaked_workers(art)
            finally:
                try:
                    art.kill(controller)
                except Exception:  # noqa: BLE001
                    pass
        if result.error is not None:
            raise result.error
        return result


    # A controller usually dies WITH its node — often in the same event
    # (GCS restart, head blip) that makes the first cleanup RPCs fail.
    # The GCS persists and daemons reconnect well within this window,
    # so the sweeps retry with backoff instead of leaking the gang.
    _CLEANUP_RETRY_WINDOW_S = 30.0

    @classmethod
    def _retry_cleanup(cls, what: str, sweep) -> None:
        """Run ``sweep`` until it succeeds or the GCS-restart window
        closes (capped exponential backoff between tries)."""
        import time as _time  # noqa: PLC0415

        deadline = _time.monotonic() + cls._CLEANUP_RETRY_WINDOW_S
        delay = 0.25
        while True:
            try:
                sweep()
                return
            except Exception as e:  # noqa: BLE001 — GCS may be restarting
                if _time.monotonic() >= deadline:
                    logger.warning("%s failed (giving up after %.0fs): %s",
                                   what, cls._CLEANUP_RETRY_WINDOW_S, e)
                    return
                logger.info("%s hit %s; retrying in %.2fs", what, e, delay)
                _time.sleep(delay)
                delay = min(delay * 2, 4.0)

    def _release_leaked_groups(self, art) -> None:
        """A controller that died with its node never ran its PG
        release — remove this run's leftover reservations so the
        recreated controller's gang can actually place (there is no
        GCS owner-fate-sharing for placement groups)."""
        from ant_ray_tpu._private.ids import PlacementGroupID  # noqa: PLC0415
        from ant_ray_tpu.util.placement_group import (  # noqa: PLC0415
            PlacementGroup,
            placement_group_table,
            remove_placement_group,
        )

        pg_name = self._run_config.pg_name()

        def sweep():
            my_job_hex = self._my_job_hex()
            for pg_hex, rec in placement_group_table().items():
                if rec.get("name") != pg_name or \
                        rec.get("state") == "REMOVED":
                    continue
                if self._foreign_job(rec, my_job_hex):
                    continue
                remove_placement_group(PlacementGroup(
                    id=PlacementGroupID.from_hex(pg_hex),
                    bundles=tuple(rec.get("bundles", ())),
                    strategy=rec.get("strategy", "PACK")))

        self._retry_cleanup("leaked placement-group cleanup", sweep)

    @staticmethod
    def _my_job_hex() -> str | None:
        from ant_ray_tpu.api import global_worker  # noqa: PLC0415

        my_job = getattr(global_worker.runtime, "job_id", None)
        return my_job.hex() if my_job is not None else None

    @staticmethod
    def _foreign_job(rec: dict, my_job_hex: str | None) -> bool:
        """Cleanup scope: another job's same-named run keeps its
        reservations and workers (one rule for both cleanups)."""
        return (rec.get("job_id") is not None and my_job_hex is not None
                and rec["job_id"] != my_job_hex)

    def _kill_leaked_workers(self, art) -> None:
        """Kill this run's surviving TrainWorker actors by their
        "<pg_name>-w" name prefix — a PG-less run (world<=1, no TPU)
        has no placement group whose removal would take them down, so
        they would otherwise hold their resources until job teardown."""
        from ant_ray_tpu._private.ids import ActorID  # noqa: PLC0415
        from ant_ray_tpu.api import global_worker  # noqa: PLC0415

        prefix = f"{self._run_config.pg_name()}-w"

        def sweep():
            my_job_hex = self._my_job_hex()
            gcs = global_worker.runtime._gcs
            for rec in gcs.call("ListActors", retries=3):
                if not (rec.get("name") or "").startswith(prefix) or \
                        rec.get("state") == "DEAD":
                    continue
                if self._foreign_job(rec, my_job_hex):
                    continue
                gcs.call("KillActor", {
                    "actor_id": ActorID.from_hex(rec["actor_id"]),
                    "no_restart": True}, retries=3)

        self._retry_cleanup("leaked worker cleanup", sweep)


# Alias mirroring the reference's generic data-parallel trainer name.
DataParallelTrainer = JaxTrainer
TpuTrainer = JaxTrainer
