"""JaxTrainer — the user-facing distributed trainer
(ref: train/v2/jax/jax_trainer.py:19 + api/data_parallel_trainer.py:155).
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from ant_ray_tpu.train.config import Result, RunConfig, ScalingConfig

logger = logging.getLogger(__name__)


class JaxTrainer:
    """Distributed training driver: one actor per worker (per TPU host in
    a slice), rendezvous, metric/checkpoint reporting, elastic restarts.

    Example::

        def train_loop(config):
            ctx = train.get_context()
            for step in range(config["steps"]):
                ...
                train.report({"loss": loss}, checkpoint=params)

        trainer = JaxTrainer(
            train_loop,
            train_loop_config={"steps": 100},
            scaling_config=ScalingConfig(num_workers=4, use_tpu=True,
                                         topology="4x8"),
        )
        result = trainer.fit()
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None):
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()

    def fit(self) -> Result:
        import ant_ray_tpu as art  # noqa: PLC0415
        from ant_ray_tpu.train.controller import TrainController  # noqa: PLC0415

        if not art.is_initialized():
            art.init()
        controller_cls = art.remote(TrainController).options(
            max_concurrency=8, num_cpus=0)
        controller = controller_cls.remote(
            self._loop, self._loop_config, self._scaling, self._run_config)
        try:
            result: Result = art.get(
                controller.run.remote(controller), timeout=None)
        finally:
            try:
                art.kill(controller)
            except Exception:  # noqa: BLE001
                pass
        if result.error is not None:
            raise result.error
        return result


# Alias mirroring the reference's generic data-parallel trainer name.
DataParallelTrainer = JaxTrainer
TpuTrainer = JaxTrainer
