"""TrainController + worker group — the driving actors of a training run
(ref: train/v2/_internal/execution/controller/controller.py:101 control
loop :505-527, worker_group.py:269,376-391).

The controller is an actor (max_concurrency > 1 so workers can report
while the control loop blocks), the worker group is one actor per rank.
Failure handling: a dead worker fails the epoch; the controller restarts
the whole group up to FailureConfig.max_failures, handing the latest
checkpoint to the restarted loop (elastic recovery — ref:
failure_handling/).
"""

from __future__ import annotations

import logging
import os
import threading
import time

from ant_ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    pack_checkpoint_dir,
    save_pytree,
)
from ant_ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ant_ray_tpu.train.session import (
    PreemptionInterrupt,
    TrainContext,
    _set_context,
)

logger = logging.getLogger(__name__)

# Sentinel return of a worker that unwound on a drain notice (its last
# report's checkpoint is registered; nothing was lost).
_PREEMPTED = "__preempted__"


class _DrainRestart(Exception):
    """Group interrupted by a node drain — relaunch off the draining
    node WITHOUT consuming a failure-budget attempt or a backoff wait
    (the workers checkpointed and exited cleanly)."""


class TrainWorker:
    """One rank of the worker group (actor)."""

    def __init__(self, rank: int, world_size: int, storage_path: str,
                 experiment_name: str, use_tpu: bool,
                 num_slices: int = 1):
        self._rank = rank
        self._world_size = world_size
        self._storage_path = storage_path
        self._experiment_name = experiment_name
        self._use_tpu = use_tpu
        self._num_slices = num_slices

    def propose_coordinator(self) -> str:
        """Rank 0 advertises host:port for the jax.distributed
        coordination service (ref: rank-0 address broadcast,
        train/v2/jax/config.py:103)."""
        import socket  # noqa: PLC0415

        from ant_ray_tpu._private.protocol import find_free_port  # noqa: PLC0415

        try:
            host = socket.gethostbyname(socket.gethostname())
        except OSError:
            host = "127.0.0.1"
        return f"{host}:{find_free_port()}"

    def setup_distributed(self, coordinator: str | None) -> bool:
        """jax.distributed rendezvous for multi-host slices (ref:
        train/v2/jax/config.py:30,73).  Degrades gracefully where the
        coordination service is unavailable (single-host)."""
        if not self._use_tpu or self._world_size == 1 or coordinator is None:
            return False
        try:
            from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

            jax = import_jax()
            jax.distributed.initialize(
                coordinator, num_processes=self._world_size,
                process_id=self._rank)
            return jax.process_count() == self._world_size
        except Exception as e:  # noqa: BLE001
            logger.warning("jax.distributed init failed (%s); continuing "
                           "single-process", e)
            return False

    def run(self, loop_fn, loop_config, controller, latest_checkpoint,
            attempt: int = 0, dataset_shards: dict | None = None):
        topo = None
        if (self._num_slices > 1
                and self._world_size % self._num_slices == 0):
            # Contiguous rank blocks per slice — matches the multi-slice
            # PG's bundle layout (bundle s*hosts+i = host i of slice s),
            # so sync_gradients' hierarchical allreduce keeps its DCN
            # exchange to one message per slice.
            from ant_ray_tpu.util.collective.types import SliceTopology  # noqa: PLC0415

            topo = SliceTopology.regular(self._world_size,
                                         self._num_slices)
        ctx = TrainContext(
            world_rank=self._rank,
            world_size=self._world_size,
            local_rank=0,
            experiment_name=self._experiment_name,
            storage_path=self._storage_path,
            controller=controller,
            latest_checkpoint=latest_checkpoint,
            attempt=attempt,
            use_tpu=self._use_tpu,
            slice_topology=topo,
            dataset_shards=dataset_shards or {},
        )
        _set_context(ctx)
        try:
            if loop_config is None:
                return loop_fn()
            return loop_fn(loop_config)
        except PreemptionInterrupt:
            # Controlled drain exit: the controller told report() to
            # stop; the checkpoint that report carried is registered.
            return _PREEMPTED
        finally:
            _set_context(None)  # type: ignore[arg-type]

    def ping(self):
        return "pong"


class TrainController:
    """Detached driving actor of one training run."""

    def __init__(self, loop_fn, loop_config, scaling: ScalingConfig,
                 run_config: RunConfig, resume: bool = False,
                 run_token: str | None = None, datasets: dict | None = None,
                 data_config=None):
        self._loop_fn = loop_fn
        self._loop_config = loop_config
        self._scaling = scaling
        self._run_config = run_config
        self._datasets = datasets or {}
        self._data_config = data_config
        self._storage_path = run_config.resolved_storage_path()
        self._ckpt_manager = CheckpointManager(
            self._storage_path, run_config.checkpoint_config.num_to_keep,
            restore=resume, run_token=run_token)
        self._metrics_history: list[dict] = []
        self._latest_metrics: dict = {}
        # rank -> latest step record dict (observability/step_profiler);
        # folded into cluster gauges on every report.
        self._step_records: dict[int, dict] = {}
        self._step_gauges = None
        # Resume past any on-disk checkpoints (a recreated controller
        # must not reuse their directories).
        self._report_index = self._ckpt_manager.next_index
        self._lock = threading.Lock()
        # Drain plane: set by the drain monitor when a node hosting the
        # gang got a preemption notice; report() acks carry it to every
        # rank, whose next report becomes the zero-step-loss exit.
        self._drain_stop = False
        self._drain_deadline_ts = 0.0
        # Async checkpoint plane: one background save thread (order-
        # preserving) + in-flight save futures the restart/result paths
        # flush before reading `latest`.
        self._save_pool = None
        self._pending_saves: list = []

    # ---- called by workers (concurrently with run())

    def report_from_worker(self, rank: int, metrics: dict, checkpoint):
        step_record = metrics.pop("_step_record", None)
        with self._lock:
            if step_record is not None:
                self._step_records[rank] = step_record
            if rank == 0:
                self._latest_metrics = metrics
                self._metrics_history.append(metrics)
                if checkpoint is not None:
                    self._accept_checkpoint(checkpoint)
                self._report_index += 1
        # Emit once per step, not once per rank-report: N ranks each
        # re-aggregating N records would make telemetry cost quadratic
        # in world size.  The lowest rank carrying records is the
        # designated emitter (rank 0 normally; still works if only a
        # subset of ranks runs a profiler).
        if step_record is not None and rank == min(self._step_records):
            self._emit_step_gauges()
        # The ack doubles as the drain channel (see session.report).
        return {"ok": True, "stop": self._drain_stop}

    # ---- checkpoint save/replication (CheckpointConfig knobs)

    def _accept_checkpoint(self, checkpoint) -> None:
        """Queue or perform the save+replicate+register of a reported
        checkpoint.  Called under self._lock (report path)."""
        cfg = self._run_config.checkpoint_config
        if isinstance(checkpoint, Checkpoint):
            # Already a directory handle: nothing to save off-thread —
            # but the optional replication pack is real I/O, and a
            # mixed run (pytree reports queued behind this one) must
            # register in REPORT order or `latest` regresses when the
            # queued save lands later.  Under async_save both concerns
            # route it through the same single-thread pool.
            if not getattr(cfg, "async_save", True):
                self._finish_checkpoint(checkpoint,
                                        registered_under_lock=True)
                return
            self._ensure_save_pool()
            self._pending_saves = [f for f in self._pending_saves
                                   if not f.done()]
            self._pending_saves.append(
                self._save_pool.submit(self._finish_checkpoint,
                                       checkpoint))
            return
        path = self._ckpt_manager.next_checkpoint_dir(self._report_index)
        if not getattr(cfg, "async_save", True):
            save_pytree(checkpoint, path)
            self._finish_checkpoint(Checkpoint.from_directory(path),
                                    registered_under_lock=True)
            return
        # Background save: the report RPC (and with it the gang's step
        # loop) returns immediately; the single-thread pool preserves
        # report order, and `latest` only ever sees COMPLETED saves —
        # a controller restart flushes the queue first, so restore can
        # never adopt a torn save.
        self._ensure_save_pool()

        def _save(tree=checkpoint, path=path):
            try:
                save_pytree(tree, path)
            except Exception:  # noqa: BLE001 — a failed save must not
                logger.exception(   # kill the save thread; the PREVIOUS
                    "background checkpoint save to %s failed", path)
                return              # checkpoint stays `latest`
            self._finish_checkpoint(Checkpoint.from_directory(path))

        self._pending_saves = [f for f in self._pending_saves
                               if not f.done()]
        self._pending_saves.append(self._save_pool.submit(_save))

    def _ensure_save_pool(self) -> None:
        if self._save_pool is None:
            from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415

            self._save_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="art-ckpt-save")

    def _finish_checkpoint(self, ckpt: Checkpoint,
                           registered_under_lock: bool = False) -> None:
        """Replicate (best-effort) then register a COMPLETED save."""
        if getattr(self._run_config.checkpoint_config, "replicate", True) \
                and not os.path.isdir(ckpt.path):
            # A directory handle the controller can't see (worker-local
            # path, no shared storage): nothing to pack from here —
            # skip quietly rather than raise-and-warn every report.
            logger.debug("checkpoint %s not visible from the controller; "
                         "skipping replication", ckpt.path)
        elif getattr(self._run_config.checkpoint_config, "replicate", True):
            try:
                import ant_ray_tpu as art  # noqa: PLC0415

                ckpt = ckpt.with_replica(
                    art.put(pack_checkpoint_dir(ckpt.path)))
            except Exception as e:  # noqa: BLE001 — replication is a
                # durability bonus; the on-disk copy is the authority.
                logger.warning("checkpoint replication failed: %s", e)
        if registered_under_lock:
            self._ckpt_manager.register(ckpt)
        else:
            with self._lock:
                self._ckpt_manager.register(ckpt)

    def _flush_checkpoints(self, timeout: float = 300.0) -> None:
        """Wait for in-flight background saves — every path that READS
        ``latest`` (group restart, fit result) flushes first, so a
        restore reflects every acked report."""
        with self._lock:
            pending, self._pending_saves = self._pending_saves, []
        for fut in pending:
            try:
                fut.result(timeout=timeout)
            except Exception:  # noqa: BLE001 — logged by the save job
                pass

    # ---- step telemetry (observability/step_profiler.py records)

    def get_step_summary(self) -> dict:
        """Cross-rank aggregation of each rank's LATEST step record:
        step-time mean/p50/max, mean per-phase fractions, and the
        straggler ratio (max/median step time — 1.0 means a perfectly
        even gang; arXiv:2510.20171's skew telemetry)."""
        with self._lock:
            records = dict(self._step_records)
        if not records:
            return {"ranks": 0}
        times = sorted(float(r.get("total_s", 0.0))
                       for r in records.values())
        n = len(times)
        # True median — an even gang (the common case: 2 hosts) must
        # not read the max as "median" and report skew=1.0 forever.
        median = (times[(n - 1) // 2] + times[n // 2]) / 2
        out: dict = {
            "ranks": n,
            "step_time_mean_s": sum(times) / n,
            "step_time_p50_s": median,
            "step_time_max_s": times[-1],
            "skew_ratio": (times[-1] / median) if median > 0 else 1.0,
        }
        names: set = set()
        for r in records.values():
            names.update(r.get("phases") or {})
        for name in sorted(names):
            fracs = []
            for r in records.values():
                total = float(r.get("total_s", 0.0))
                sec = float((r.get("phases") or {}).get(name, 0.0))
                fracs.append(min(1.0, sec / total) if total > 0 else 0.0)
            out[f"phase_{name}_fraction"] = sum(fracs) / n
        mfus = [r.get("mfu") for r in records.values()
                if r.get("mfu") is not None]
        if mfus:
            out["mfu_mean"] = sum(mfus) / len(mfus)
        return out

    def _emit_step_gauges(self) -> None:
        """Publish the cross-rank aggregation as cluster gauges (best
        effort, metrics-style — a no-op when emission is disabled or
        the worker is not connected)."""
        if not getattr(self._run_config, "step_metrics", True):
            return
        summary = self.get_step_summary()
        if not summary.get("ranks"):
            return
        try:
            from ant_ray_tpu.util.metrics import Gauge  # noqa: PLC0415

            if self._step_gauges is None:
                run = self._run_config.name or "run"
                self._step_gauges = {
                    "time": Gauge(
                        "art_train_step_time_s",
                        description="train step time across ranks",
                        tag_keys=("run", "stat")).set_default_tags(
                            {"run": run}),
                    "phase": Gauge(
                        "art_train_step_phase_fraction",
                        description="mean fraction of step time per "
                                    "phase",
                        tag_keys=("run", "phase")).set_default_tags(
                            {"run": run}),
                    "skew": Gauge(
                        "art_train_step_skew_ratio",
                        description="straggler gauge: max/median step "
                                    "time over ranks",
                        tag_keys=("run",)).set_default_tags(
                            {"run": run}),
                    "mfu": Gauge(
                        "art_train_step_mfu",
                        description="mean MFU across ranks",
                        tag_keys=("run",)).set_default_tags(
                            {"run": run}),
                }
            g = self._step_gauges
            for stat in ("mean", "p50", "max"):
                g["time"].set(summary[f"step_time_{stat}_s"],
                              tags={"stat": stat})
            for key, value in summary.items():
                if key.startswith("phase_") and key.endswith("_fraction"):
                    g["phase"].set(
                        value, tags={"phase": key[len("phase_"):
                                                  -len("_fraction")]})
            g["skew"].set(summary["skew_ratio"])
            if "mfu_mean" in summary:
                g["mfu"].set(summary["mfu_mean"])
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    def get_metrics_history(self):
        with self._lock:
            return list(self._metrics_history)

    # ---- control loop

    def run(self, self_handle):
        import ant_ray_tpu as art  # noqa: PLC0415

        from ant_ray_tpu.train.scaling_policy import policy_for  # noqa: PLC0415

        policy = policy_for(self._scaling)
        failure_config: FailureConfig = self._run_config.failure_config
        attempts = failure_config.max_failures + 1
        last_error: Exception | None = None
        failures = 0
        incarnation = 0       # every launch, drains included — feeds
        while True:           # attempt-unique collective-group names
            world = policy.workers_for_attempt(
                self._scaling, art.available_resources(),
                art.cluster_resources(), attempt=failures)
            try:
                self._run_worker_group(art, self_handle, world,
                                       incarnation)
                return self._result(error=None)
            except _DrainRestart as e:
                # An ANNOUNCED departure costs neither a failure-budget
                # attempt nor a backoff wait: every rank checkpointed
                # through its last report and exited cleanly, and the
                # draining node is already fenced off the scheduler —
                # relaunch immediately, resuming at the exact step.
                incarnation += 1
                logger.info(
                    "worker group drained (%s); relaunching off the "
                    "draining node (failure budget untouched: %d/%d)",
                    e, failures, attempts - 1)
                continue
            # RuntimeError covers gang-reservation failures (an
            # infeasible PG after a node died is an attempt, not a
            # crash of the controller itself).
            except (art.exceptions.ArtError, RuntimeError) as e:
                last_error = e
                failures += 1
                incarnation += 1
                if (hasattr(policy, "note_unplaceable")
                        and isinstance(e, RuntimeError)
                        and ("reserve" in str(e)
                             or "infeasible" in str(e))):
                    # Aggregate capacity over-estimated placeability
                    # (fragmentation): converge downward.
                    policy.note_unplaceable(world)
                logger.warning(
                    "worker group (world=%d) failed (attempt %d/%d): %s",
                    world, failures, attempts, e)
                if failures >= attempts:
                    return self._result(error=last_error)
                # Give failure detection a beat: the next attempt's
                # capacity read must see the dead node as dead, or an
                # elastic resize would re-request the old world size.
                # Capped exponential backoff + jitter (FailureConfig.
                # group_restart_backoff_s) so a crash-looping gang
                # doesn't hammer the scheduler at a fixed cadence.
                time.sleep(self._restart_backoff_s(failure_config,
                                                   failures))

    def _restart_backoff_s(self, failure_config, failures: int) -> float:
        import random  # noqa: PLC0415

        base = getattr(failure_config, "group_restart_backoff_s", 2.0)
        if not getattr(self._scaling, "min_workers", 0):
            # Fixed-size groups don't resize by a capacity read, so
            # they keep the historical snappy retry: a quarter of the
            # base (0.5s at the default), scaling with the knob.
            base = base / 4.0
        delay = min(base * (2 ** (failures - 1)), base * 16, 60.0)
        return delay * random.uniform(0.8, 1.2)

    def _run_worker_group(self, art, self_handle, world: int | None = None,
                          attempt: int = 0):
        from ant_ray_tpu.api import remote  # noqa: PLC0415

        scaling = self._scaling
        world = world if world is not None else scaling.num_workers
        self._drain_stop = False      # fresh gang, fresh drain state
        self._drain_deadline_ts = 0.0
        pg, slice_pg = self._reserve_gang(scaling, world)
        self._worker_pg = pg          # set BEFORE anything can fail, so
        self._worker_slice = slice_pg  # the finally always releases it
        workers = []
        drain_watch_stop = threading.Event()
        try:
            base_opts = {"resources": scaling.worker_resources(),
                         "num_cpus": 0}
            worker_cls = remote(TrainWorker)
            import uuid as _uuid  # noqa: PLC0415

            # Unique per-incarnation names: the trainer's leaked-worker
            # cleanup after a controller death finds survivors by the
            # "<pg_name>-w" prefix (a PG-less world<=1 run has no
            # placement group whose removal would kill them).
            tag = _uuid.uuid4().hex[:4]
            workers = [
                worker_cls.options(
                    **base_opts,
                    name=f"{self._run_config.pg_name()}-w{rank}-{tag}",
                    placement_group=pg,
                    # Rank r on bundle r: with a slice PG this pins rank
                    # r to the slice host with tpu-worker-id == r (ICI
                    # layout).
                    placement_group_bundle_index=(
                        rank if pg is not None else -1),
                ).remote(rank, world,
                         self._storage_path,
                         self._run_config.name or "run",
                         scaling.use_tpu,
                         getattr(scaling, "num_slices", 1))
                for rank in range(world)
            ]
            # Rendezvous: rank 0's host coordinates (multi-host slices).
            coordinator = None
            if scaling.use_tpu and world > 1:
                coordinator = art.get(
                    workers[0].propose_coordinator.remote())
            art.get([w.setup_distributed.remote(coordinator)
                     for w in workers])
            # Adopt every acked report before reading `latest` — an
            # async save still in flight from the PREVIOUS incarnation
            # must land first or the resume point regresses.
            self._flush_checkpoints()
            latest = self._ckpt_manager.latest
            shards = self._make_dataset_shards(art, world)
            run_refs = [
                w.run.remote(self._loop_fn, self._loop_config,
                             self_handle, latest, attempt, shards[rank])
                for rank, w in enumerate(workers)
            ]
            # Preemption watcher: a drain notice on any node hosting a
            # gang worker flips _drain_stop, which the report acks
            # relay to every rank (see session.report).
            threading.Thread(
                target=self._watch_for_drain,
                args=(art, drain_watch_stop,
                      {f"{self._run_config.pg_name()}-w{rank}-{tag}"
                       for rank in range(world)}),
                daemon=True, name="art-train-drain-watch").start()
            # Fail FAST on the first rank failure (ref: worker_group
            # poll_status aborts the group on any error) — a plain
            # gather would sit behind the healthy ranks' remaining work
            # before surfacing a death, delaying recovery by minutes.
            # The short wait timeout is the drain poll: on _drain_stop
            # the loop keeps collecting ranks until the drain deadline,
            # then abandons stragglers (the finally kills them — their
            # progress is already checkpointed through rank 0).
            pending = list(run_refs)
            interrupted = False
            while pending:
                done, pending = art.wait(pending, num_returns=1,
                                         timeout=0.5)
                if done and art.get(done[0]) == _PREEMPTED:
                    interrupted = True
                if self._drain_stop and pending and \
                        time.time() >= self._drain_deadline_ts:
                    logger.warning(
                        "drain deadline passed with %d rank(s) still "
                        "running; abandoning them (progress is "
                        "checkpointed)", len(pending))
                    interrupted = True
                    break
            # Restart ONLY if a rank actually unwound on the notice: a
            # drain observed after every rank already finished its loop
            # is a completed fit, not one to re-execute.
            if self._drain_stop and interrupted:
                raise _DrainRestart(
                    "preemption notice on a gang node")
        finally:
            drain_watch_stop.set()
            for w in workers:
                try:
                    art.kill(w)
                except Exception:  # noqa: BLE001
                    pass
            self._release_gang()
            self._kill_data_coordinators(art)

    def _watch_for_drain(self, art, stop: threading.Event,
                         worker_names: set) -> None:
        """Poll node drain state while a gang runs; when a DRAINING
        node hosts one of this gang's workers, order the proactive
        stop.  Every rank then unwinds at its next report — WITH its
        checkpoint registered — and the control loop relaunches the
        gang on the remaining nodes before the announced deadline."""
        from ant_ray_tpu.api import global_worker  # noqa: PLC0415

        while not stop.wait(0.5):
            if self._drain_stop:
                return
            try:
                draining = {n["NodeID"]: n.get("DrainDeadline", 0.0)
                            for n in art.nodes()
                            if n["Alive"] and n.get("Draining")}
                if not draining:
                    continue
                gcs = global_worker.runtime._gcs
                hit = [rec for rec in gcs.call("ListActors", retries=3)
                       if (rec.get("name") or "") in worker_names
                       and rec.get("state") != "DEAD"
                       and rec.get("node_id") in draining]
                if not hit:
                    continue
                deadline = min(filter(None,
                                      (draining[r["node_id"]]
                                       for r in hit)),
                               default=0.0)
                # A watcher from a PREVIOUS incarnation can reach here
                # seconds after its gang ended (ListActors retries) —
                # it must not drain-stop the fresh gang, which was
                # already placed off the draining node.
                if stop.is_set():
                    return
                # No announced deadline -> a generous local one: the
                # stop order still reaches ranks at their next report.
                self._drain_deadline_ts = deadline or (time.time() + 30.0)
                self._drain_stop = True
                logger.warning(
                    "drain notice on node(s) hosting %d gang worker(s); "
                    "ordering proactive checkpoint + migration "
                    "(deadline in %.0fs)", len(hit),
                    self._drain_deadline_ts - time.time())
                return
            except Exception as e:  # noqa: BLE001 — monitoring only
                logger.debug("drain watch poll failed: %s", e)

    def _make_dataset_shards(self, art, world: int) -> list:
        """Per-rank {name: DataIterator} from the trainer's datasets=.
        Fresh coordinators every attempt: a restarted (possibly
        resized) gang re-splits the stream across the NEW world size —
        a dead rank's unconsumed shard is thereby reassigned (ref:
        DataConfig.configure runs per worker-group start,
        train/v2/api/data_parallel_trainer.py:83)."""
        if not self._datasets:
            return [None] * world
        from ant_ray_tpu.data.iterator import make_streaming_split  # noqa: PLC0415
        from ant_ray_tpu.train.config import DataConfig  # noqa: PLC0415

        cfg = self._data_config or DataConfig()
        self._kill_data_coordinators(art)   # previous attempt's actors
        coords = []
        shards: list[dict] = [dict() for _ in range(world)]
        for name, ds in self._datasets.items():
            if cfg.splits(name):
                its = make_streaming_split(ds, world, equal=cfg.equal,
                                           name=name)
                coords.append(its[0]._coord)
                for rank in range(world):
                    shards[rank][name] = its[rank]
            else:
                for rank in range(world):
                    shards[rank][name] = ds.iterator()
            if cfg.device_feed:
                # Forward per-worker device-feed defaults (incl. rank/
                # world, so a callable sharding resolves per worker on
                # its own devices) — the loop then just calls
                # get_dataset_shard(name).iter_device_batches().
                for rank in range(world):
                    # dict-merge (not kwargs) so a user-supplied rank/
                    # world in device_feed is overridden, not a
                    # TypeError; the controller's values are the truth.
                    shards[rank][name].configure_device_feed(
                        **{**cfg.device_feed,
                           "rank": rank, "world": world})
        self._data_coords = coords
        return shards

    def _kill_data_coordinators(self, art) -> None:
        for coord in getattr(self, "_data_coords", ()):
            try:
                art.kill(coord)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        self._data_coords = []

    def _reserve_gang(self, scaling, world: int | None = None):
        """Gang-reserve the worker group's resources before spawning any
        rank (ref: WorkerGroup placement-group creation,
        worker_group.py:269).  TPU + topology ⇒ reserve a whole slice
        (slice_placement_group); otherwise a plain PG with the scaling
        config's strategy.  Single local worker ⇒ no PG (keeps the
        laptop path free of reservation latency)."""
        world = world if world is not None else scaling.num_workers
        if scaling.use_tpu and scaling.topology:
            num_slices = getattr(scaling, "num_slices", 1)
            if num_slices > 1:
                from ant_ray_tpu.util.tpu import (  # noqa: PLC0415
                    multi_slice_placement_group,
                )

                extra = {k: v
                         for k, v in scaling.worker_resources().items()
                         if k != "TPU"}
                ms_pg = multi_slice_placement_group(
                    scaling.topology, num_slices,
                    scaling.accelerator_type,
                    name=self._run_config.pg_name(),
                    bundle_extra=extra)
                if scaling.num_workers != ms_pg.num_hosts:
                    ms_pg.remove()
                    raise ValueError(
                        f"num_workers={scaling.num_workers} does not "
                        f"match the {ms_pg.num_hosts} hosts of "
                        f"{num_slices}x slice {scaling.topology}")
                if not ms_pg.ready(timeout=120):
                    ms_pg.remove()
                    raise RuntimeError(
                        f"could not reserve {num_slices} TPU slices of "
                        f"{scaling.topology}")
                return ms_pg.placement_group, ms_pg
            from ant_ray_tpu.util.tpu import slice_placement_group  # noqa: PLC0415

            # Bundles must cover everything a rank actor demands — the
            # chips AND its CPU share — or the bundle lease rejects it.
            extra = {k: v for k, v in scaling.worker_resources().items()
                     if k != "TPU"}
            slice_pg = slice_placement_group(
                scaling.topology, scaling.accelerator_type,
                name=self._run_config.pg_name(),
                bundle_extra=extra)
            if scaling.num_workers != slice_pg.num_hosts:
                slice_pg.remove()
                raise ValueError(
                    f"num_workers={scaling.num_workers} does not match "
                    f"the {slice_pg.num_hosts} hosts of slice "
                    f"{scaling.topology}")
            if not slice_pg.ready(timeout=120):
                slice_pg.remove()
                raise RuntimeError(
                    f"could not reserve TPU slice {scaling.topology}")
            return slice_pg.placement_group, slice_pg
        if world <= 1:
            return None, None
        from ant_ray_tpu.util.placement_group import placement_group  # noqa: PLC0415

        pg = placement_group(
            [scaling.worker_resources()
             for _ in range(world)],
            strategy=scaling.placement_strategy,
            name=self._run_config.pg_name())
        # Elastic groups fail reservations fast — a shrunken cluster
        # should trigger a resize within seconds, not after a two-minute
        # stall on an unplaceable gang.
        ready_timeout = 20 if getattr(scaling, "min_workers", 0) else 120
        if not pg.ready(timeout=ready_timeout):
            from ant_ray_tpu.util.placement_group import (  # noqa: PLC0415
                remove_placement_group,
            )

            remove_placement_group(pg)  # don't leak a PENDING reservation
            raise RuntimeError("could not reserve training worker group")
        return pg, None

    def _release_gang(self):
        pg = getattr(self, "_worker_pg", None)
        self._worker_pg = None
        self._worker_slice = None
        if pg is not None:
            from ant_ray_tpu.util.placement_group import (  # noqa: PLC0415
                remove_placement_group,
            )

            try:
                remove_placement_group(pg)
            except Exception:  # noqa: BLE001 — release is best-effort
                pass

    def _result(self, error):
        from ant_ray_tpu.train.config import Result  # noqa: PLC0415

        # Every acked report's checkpoint must be visible in the
        # result, async saves included.
        self._flush_checkpoints()
        return Result(
            metrics=dict(self._latest_metrics),
            checkpoint=self._ckpt_manager.latest,
            error=error,
            path=self._storage_path,
        )
