"""Distributed training (ref capability: ray.train v2 — JaxTrainer path)."""

from ant_ray_tpu.train.checkpoint import Checkpoint, load_pytree, save_pytree
from ant_ray_tpu.train.config import (
    CheckpointConfig,
    DataConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ant_ray_tpu.train.session import (
    PreemptionInterrupt,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_world_rank,
    get_world_size,
    gradient_syncer,
    report,
    sync_gradients,
)
from ant_ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, TpuTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "DataConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxTrainer",
    "PreemptionInterrupt",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TpuTrainer",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "get_world_rank",
    "get_world_size",
    "gradient_syncer",
    "load_pytree",
    "report",
    "save_pytree",
    "sync_gradients",
]
