"""Train configuration dataclasses (ref: train/v2/api/config.py —
ScalingConfig TPU fields :73-74, RunConfig, FailureConfig)."""

from __future__ import annotations

import dataclasses
import os
import tempfile


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each needs.

    TPU-native fields mirror the reference's ScalingConfig(use_tpu=True,
    topology="4x8"): one worker per TPU host in a slice, chips bound via
    the TPU resource.
    """

    num_workers: int = 1
    # Elastic scaling (ref: scaling_policy/): 0 = fixed group size;
    # >0 = the group may launch/relaunch with as few as min_workers
    # ranks when the cluster can't place num_workers, growing back on
    # later restarts.  Incompatible with a whole-slice topology.
    min_workers: int = 0
    # Multi-slice training: the gang spans this many accelerator
    # slices (num_workers % num_slices == 0, contiguous rank blocks per
    # slice).  >1 feeds sync_gradients a SliceTopology so the fused
    # allreduce runs its two-level intra-slice (ICI) / inter-slice
    # (DCN) schedule, and with use_tpu the gang reserves one placement
    # group per slice (co-located by tpu-pod-name).
    num_slices: int = 1
    use_tpu: bool = False
    topology: str = ""                  # e.g. "4x8" (whole-slice reservation)
    accelerator_type: str = "TPU-V5E"   # generation for slice math
    chips_per_worker: int = 0           # TPU chips each worker binds (0=all)
    resources_per_worker: dict = dataclasses.field(default_factory=dict)
    placement_strategy: str = "PACK"

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker)
        if self.use_tpu and self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        res.setdefault("CPU", 1.0)
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0               # worker-group restarts allowed
    # Controller recreations after the controller ACTOR itself dies
    # (node loss); a separate budget — multiplying it into max_failures
    # would turn 2 worker retries into 9 gang launches.
    max_controller_failures: int = 1
    # Base wait between group-restart attempts after a FAILURE: gives
    # failure detection a beat so the next capacity read sees the dead
    # node as dead.  Grows exponentially (x2 per consecutive failure,
    # capped at 16x base, +/-20% jitter so restarting gangs don't
    # stampede the scheduler in lockstep).  Drain-triggered restarts
    # skip the wait entirely — the workers checkpointed and exited
    # cleanly, and the draining node is already fenced off.
    group_restart_backoff_s: float = 2.0


@dataclasses.dataclass
class DataConfig:
    """How the trainer's ``datasets=`` feed the workers (ref:
    train/_internal/data_config.py — DataConfig.configure).

    Datasets named in ``datasets_to_split`` ("all" = every dataset) are
    streaming_split across ranks with ``equal=True`` (every rank gets
    the same row count per epoch — SPMD lockstep must not starve a
    rank); the rest are broadcast whole to every worker (e.g. a small
    validation set)."""

    datasets_to_split: "str | list[str]" = "all"
    equal: bool = True
    # Defaults forwarded to every shard's configure_device_feed(), so a
    # worker loop can call get_dataset_shard(name).iter_device_batches()
    # with no arguments and get prefetched host→HBM delivery.  Keys:
    # batch_size, prefetch_batches, sharding, collate_fn, pad_value,
    # drop_last.  ``sharding`` may be a callable ``(rank, world) ->
    # jax.sharding.Sharding`` — the controller forwards each worker's
    # rank/world and the callable resolves on the worker's own devices
    # (device handles never cross processes).
    device_feed: dict | None = None

    def splits(self, name: str) -> bool:
        if self.datasets_to_split == "all":
            return True
        wanted = self.datasets_to_split
        if isinstance(wanted, str):   # a single name, not a char match
            wanted = [wanted]
        return name in wanted


@dataclasses.dataclass
class CheckpointConfig:
    """Checkpoint retention + durability plane.

    ``async_save``: reported pytree checkpoints are saved by a
    controller-side background thread instead of inside the report RPC,
    so the gang's step loop never blocks on orbax/storage I/O.  Saves
    complete in report order; restore (group restart / fit result)
    waits for in-flight saves, and a torn save is never adopted — the
    on-disk rename and the run-token stamp both happen only after a
    complete write.

    ``replicate``: each completed checkpoint is also packed into the
    in-cluster object store (pulled over the bulk transfer channel,
    striped across holders) — recovery then restores at object-plane
    bandwidth from any node, and no shared ``storage_path`` is needed:
    a restarted worker whose node can't see the original directory
    materializes the checkpoint from the replica.
    """

    num_to_keep: int | None = None      # None = keep all
    async_save: bool = True
    replicate: bool = True


@dataclasses.dataclass
class RunConfig:
    name: str = ""
    storage_path: str = ""
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    # Aggregate step records (attached by session.report when the loop
    # runs a StepProfiler) into cluster Prometheus gauges —
    # art_train_step_time_s / art_train_step_phase_fraction /
    # art_train_step_skew_ratio, labeled with the run name.  Off: the
    # controller still collects records (Result-level summaries) but
    # emits nothing.
    step_metrics: bool = True

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            tempfile.gettempdir(), "art_train")
        name = self.name or "run"
        return os.path.join(base, name)

    def pg_name(self) -> str:
        """The run's placement-group name — ONE definition shared by
        gang reservation (controller) and leaked-group cleanup
        (trainer); a drifted copy would silently stop matching."""
        return f"train-{self.name or 'run'}"


@dataclasses.dataclass
class Result:
    """What fit() returns (ref: ray.train.Result)."""

    metrics: dict
    checkpoint: "object | None"
    error: Exception | None
    path: str

    @property
    def best_checkpoint(self):
        return self.checkpoint
