"""Streaming executor: pull-based block streaming with backpressure.

The reference's StreamingExecutor drives an operator topology with
resource-aware backpressure policies (ref:
data/_internal/execution/streaming_executor.py:67 +
backpressure_policy/).  Here each stage is a generator of block refs
pulling from the previous stage — demand propagates backwards, so at
most ``max_in_flight`` map tasks run per stage and at most one barrier
materializes at a time.  All-to-all stages (shuffle / sort / groupby /
repartition) run as map-reduce task graphs over ``num_returns=k``
splits, never materializing the dataset in the driver.
"""

from __future__ import annotations

import hashlib
import pickle
import random
from typing import Any, Callable, Iterable, Iterator

from ant_ray_tpu.data import block as B
from ant_ray_tpu.data import logical as L

DEFAULT_IN_FLIGHT = 8


def _stable_hash(value) -> int:
    """Deterministic across processes — builtin hash() is per-process
    randomized for strings, which would split one group over several
    hash partitions (double-counted aggregates)."""
    digest = hashlib.md5(pickle.dumps(value, protocol=4)).digest()
    return int.from_bytes(digest[:8], "big")


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


# ----------------------------------------------------------- remote fns

def _apply_fused(fused: L.FusedMap, block):
    return fused(block)


def _split_block(block, k: int, mode: str, seed):
    """Map side of a shuffle: one block → k partition pieces.

    For k == 1 the single piece is returned bare (the task runs with
    num_returns=1, where a list would be treated as one list-block)."""
    accessor = B.BlockAccessor.for_block(block)
    n = accessor.num_rows()
    if k == 1:
        return block
    if mode == "even":
        bounds = [round(i * n / k) for i in range(k + 1)]
        return [accessor.slice(bounds[i], bounds[i + 1])
                for i in range(k)]
    rows = accessor.to_rows()
    parts: list[list] = [[] for _ in range(k)]
    if mode == "random":
        # seed = (user seed, block index): distinct stream per block —
        # one shared stream would send row i of every block to the same
        # partition sequence.
        rng = random.Random(seed)
        for row in rows:
            parts[rng.randrange(k)].append(row)
    elif mode == "hash":
        key = seed  # the group key rides the seed slot
        for row in rows:
            value = key(row) if callable(key) else row[key]
            parts[_stable_hash(value) % k].append(row)
    else:  # pragma: no cover — range mode uses _split_block_range
        raise ValueError(mode)
    return [B.rows_to_block(p, block) for p in parts]


def _split_block_range(block, boundaries: list, key, descending: bool):
    """Range partition for sort: rows → len(boundaries)+1 pieces (bare
    block when there is a single piece — see _split_block)."""
    import bisect  # noqa: PLC0415

    if not boundaries:
        return block
    accessor = B.BlockAccessor.for_block(block)
    rows = accessor.to_rows()
    values = accessor.sort_key_values(key)
    k = len(boundaries) + 1
    parts: list[list] = [[] for _ in range(k)]
    for row, value in zip(rows, values):
        idx = bisect.bisect_left(boundaries, value)
        if descending:
            idx = k - 1 - idx
        parts[idx].append(row)
    return [B.rows_to_block(p, block) for p in parts]


def _merge_blocks(*pieces):
    return B.concat_blocks(list(pieces))


def _merge_shuffled(seed, *pieces):
    """Reduce side of random_shuffle: concat then Fisher-Yates within
    the partition — split alone keeps source order inside each
    partition (position-correlated training batches)."""
    merged = B.concat_blocks(list(pieces))
    rows = B.BlockAccessor.for_block(merged).to_rows()
    random.Random(seed).shuffle(rows)
    return B.rows_to_block(rows, merged)


def _merge_sorted(key, descending: bool, *pieces):
    merged = B.concat_blocks(list(pieces))
    accessor = B.BlockAccessor.for_block(merged)
    rows = accessor.to_rows()
    values = accessor.sort_key_values(key)
    order = sorted(range(len(rows)), key=values.__getitem__,
                   reverse=descending)
    return B.rows_to_block([rows[i] for i in order], merged)


def _merge_grouped(key, aggs, *pieces):
    """Reduce side of groupby: hash-partitioned rows → one row per
    group with finalized aggregates."""
    merged = B.concat_blocks(list(pieces))
    accessor = B.BlockAccessor.for_block(merged)
    groups: dict = {}
    for row in accessor.to_rows():
        group = key(row) if callable(key) else row[key]
        accs = groups.get(group)
        if accs is None:
            accs = [agg.init() for agg in aggs]
            groups[group] = accs
        for i, agg in enumerate(aggs):
            accs[i] = agg.accumulate(accs[i], agg.value_of(row))
    out = []
    key_name = key if isinstance(key, str) else "key"
    for group, accs in groups.items():
        row = {key_name: group}
        for agg, acc in zip(aggs, accs):
            row[agg.name] = agg.finalize(acc)
        out.append(row)
    return out


def _sample_keys(block, key, k: int, seed: int):
    accessor = B.BlockAccessor.for_block(block)
    values = accessor.sort_key_values(key)
    rng = random.Random(seed)
    if len(values) <= k:
        return list(values)
    return rng.sample(list(values), k)


def _block_rows(block) -> int:
    return B.BlockAccessor.for_block(block).num_rows()


def _slice_remote(block, start: int, end: int):
    return B.BlockAccessor.for_block(block).slice(start, end)


# ------------------------------------------------------------- stages

def _map_stage(upstream: Iterator, fused: L.FusedMap,
               in_flight: int) -> Iterator:
    """Ordered, bounded map over a ref stream (backpressure: at most
    ``in_flight`` outstanding tasks; upstream pulled only when a slot
    frees)."""
    art = _art()
    apply_remote = art.remote(_apply_fused)
    window: list = []
    exhausted = False
    while True:
        while not exhausted and len(window) < in_flight:
            try:
                ref = next(upstream)
            except StopIteration:
                exhausted = True
                break
            window.append(apply_remote.remote(fused, ref))
        if not window:
            return
        head = window.pop(0)
        art.wait([head], num_returns=1, timeout=600)
        yield head


def _shuffle(refs: list, k: int, mode: str, seed) -> list:
    """Generic map-reduce shuffle: split every block into k pieces, one
    merge task per partition (pieces move store-to-store, never through
    the driver).  mode="random" uses per-block split streams and a
    within-partition permutation at the merge — together a real
    two-stage uniform shuffle."""
    art = _art()
    split_remote = art.remote(_split_block).options(num_returns=k)
    merge_remote = art.remote(_merge_blocks)
    if mode == "random":
        if seed is None:  # derived streams must differ run to run
            seed = random.randrange(2**63)
        pieces = [split_remote.remote(ref, k, mode,
                                      _stable_hash(("split", seed, i)))
                  for i, ref in enumerate(refs)]
        merge_shuffled = art.remote(_merge_shuffled)
        pieces = [p if isinstance(p, list) else [p] for p in pieces]
        return [merge_shuffled.remote(_stable_hash(("merge", seed, j)),
                                      *[row[j] for row in pieces])
                for j in range(k)]
    pieces = [split_remote.remote(ref, k, mode, seed) for ref in refs]
    pieces = [p if isinstance(p, list) else [p] for p in pieces]
    return [merge_remote.remote(*[row[j] for row in pieces])
            for j in range(k)]


def _sorted_refs(refs: list, key, descending: bool) -> list:
    art = _art()
    k = max(1, len(refs))
    sample_remote = art.remote(_sample_keys)
    samples: list = []
    for chunk in art.get([sample_remote.remote(r, key, 8, i)
                          for i, r in enumerate(refs)]):
        samples.extend(chunk)
    samples.sort()
    if len(samples) > 1 and k > 1:
        step = len(samples) / k
        boundaries = [samples[min(int(step * i), len(samples) - 1)]
                      for i in range(1, k)]
    else:
        boundaries = []
    split_remote = art.remote(_split_block_range).options(
        num_returns=len(boundaries) + 1)
    merge_remote = art.remote(_merge_sorted)
    pieces = [split_remote.remote(r, boundaries, key, descending)
              for r in refs]
    pieces = [p if isinstance(p, list) else [p] for p in pieces]
    out = []
    for j in range(len(boundaries) + 1):
        out.append(merge_remote.remote(key, descending,
                                       *[row[j] for row in pieces]))
    return out


def _grouped_refs(refs: list, key, aggs) -> list:
    art = _art()
    k = max(1, len(refs))
    split_remote = art.remote(_split_block).options(num_returns=k)
    merge_remote = art.remote(_merge_grouped)
    pieces = [split_remote.remote(r, k, "hash", key) for r in refs]
    pieces = [p if isinstance(p, list) else [p] for p in pieces]
    return [merge_remote.remote(key, tuple(aggs),
                                *[row[j] for row in pieces])
            for j in range(k)]


def _limit_stage(upstream: Iterator, n: int) -> Iterator:
    art = _art()
    rows_remote = art.remote(_block_rows)
    slice_remote = art.remote(_slice_remote)
    remaining = n
    for ref in upstream:
        if remaining <= 0:
            return
        rows = art.get(rows_remote.remote(ref))
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield slice_remote.remote(ref, 0, remaining)
            remaining = 0


# ------------------------------------------------------------ executor

def execute(source: Callable[[], Iterator], operators: tuple,
            in_flight: int = DEFAULT_IN_FLIGHT) -> Iterator:
    """Stream block refs through the optimized operator chain."""
    stream: Iterator = source()
    for op in L.optimize(operators):
        if isinstance(op, L.FusedMap):
            stream = _map_stage(stream, op, in_flight)
        elif isinstance(op, L.Repartition):
            refs = list(stream)
            stream = iter(_shuffle(refs, op.num_blocks, "even", None))
        elif isinstance(op, L.RandomShuffle):
            refs = list(stream)
            k = op.num_blocks or max(1, len(refs))
            stream = iter(_shuffle(refs, k, "random", op.seed))
        elif isinstance(op, L.Sort):
            refs = list(stream)
            stream = iter(_sorted_refs(refs, op.key, op.descending))
        elif isinstance(op, L.GroupByAggregate):
            refs = list(stream)
            stream = iter(_grouped_refs(refs, op.key, op.aggs))
        elif isinstance(op, L.Limit):
            stream = _limit_stage(stream, op.n)
        else:  # pragma: no cover
            raise ValueError(f"unknown operator {op}")
    return stream
