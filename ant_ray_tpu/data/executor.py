"""Streaming executor: pull-based block streaming with backpressure.

The reference's StreamingExecutor drives an operator topology with
resource-aware backpressure policies (ref:
data/_internal/execution/streaming_executor.py:67 +
backpressure_policy/).  Here each stage is a generator of block refs
pulling from the previous stage, with two-level backpressure per stage:
at most ``data_inflight_tasks`` outstanding tasks AND (when block sizes
are known) at most ``data_inflight_bytes`` of estimated in-flight input
bytes.  All-to-all stages (shuffle / sort / groupby / repartition) run
as map-reduce task graphs over ``num_returns=k`` splits with

  * a **windowed split phase** — split tasks launch over the upstream
    with bounded in-flight work, and the driver drops each source
    block's ref as soon as its split completes, so consumed inputs are
    refcount-freed/evicted while later blocks are still arriving;
  * a **lazy merge phase** — per-partition merges launch on downstream
    demand (small lookahead for pipelining), and each merged column's
    piece refs are nulled out at launch, so finished partitions drain
    from the store while later partitions still hold their pieces.

Nothing ever materializes the dataset in the driver: the driver holds
refs only; blocks move store-to-store and spill under pressure.
"""

from __future__ import annotations

import hashlib
import pickle
import random
from collections import deque
from typing import Callable, Iterator

from ant_ray_tpu.data import block as B
from ant_ray_tpu.data import logical as L

DEFAULT_IN_FLIGHT = 8


def _stable_hash(value) -> int:
    """Deterministic across processes — builtin hash() is per-process
    randomized for strings, which would split one group over several
    hash partitions (double-counted aggregates)."""
    digest = hashlib.md5(pickle.dumps(value, protocol=4)).digest()
    return int.from_bytes(digest[:8], "big")


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


def _cfg():
    from ant_ray_tpu._private.config import global_config  # noqa: PLC0415

    return global_config()


# ----------------------------------------------------------- remote fns

def _apply_fused(fused: L.FusedMap, block):
    return fused(block)


def _split_block(block, k: int, mode: str, seed):
    """Map side of a shuffle: one block → k partition pieces.

    For k == 1 the single piece is returned bare (the task runs with
    num_returns=1, where a list would be treated as one list-block)."""
    accessor = B.BlockAccessor.for_block(block)
    n = accessor.num_rows()
    if k == 1:
        return block
    if mode == "even":
        bounds = [round(i * n / k) for i in range(k + 1)]
        return [accessor.slice(bounds[i], bounds[i + 1])
                for i in range(k)]
    rows = accessor.to_rows()
    parts: list[list] = [[] for _ in range(k)]
    if mode == "random":
        # seed = (user seed, block index): distinct stream per block —
        # one shared stream would send row i of every block to the same
        # partition sequence.
        rng = random.Random(seed)
        for row in rows:
            parts[rng.randrange(k)].append(row)
    elif mode == "hash":
        key = seed  # the group key rides the seed slot
        for row in rows:
            value = key(row) if callable(key) else row[key]
            parts[_stable_hash(value) % k].append(row)
    else:  # pragma: no cover — range mode uses _split_block_range
        raise ValueError(mode)
    return [B.rows_to_block(p, block) for p in parts]


def _split_block_range(block, boundaries: list, key, descending: bool):
    """Range partition for sort: rows → len(boundaries)+1 pieces (bare
    block when there is a single piece — see _split_block)."""
    import bisect  # noqa: PLC0415

    if not boundaries:
        return block
    accessor = B.BlockAccessor.for_block(block)
    rows = accessor.to_rows()
    values = accessor.sort_key_values(key)
    k = len(boundaries) + 1
    parts: list[list] = [[] for _ in range(k)]
    for row, value in zip(rows, values):
        idx = bisect.bisect_left(boundaries, value)
        if descending:
            idx = k - 1 - idx
        parts[idx].append(row)
    return [B.rows_to_block(p, block) for p in parts]


def _merge_blocks(*pieces):
    return B.concat_blocks(list(pieces))


def _merge_shuffled(seed, *pieces):
    """Reduce side of random_shuffle: concat then Fisher-Yates within
    the partition — split alone keeps source order inside each
    partition (position-correlated training batches)."""
    merged = B.concat_blocks(list(pieces))
    rows = B.BlockAccessor.for_block(merged).to_rows()
    random.Random(seed).shuffle(rows)
    return B.rows_to_block(rows, merged)


def _merge_sorted(key, descending: bool, *pieces):
    merged = B.concat_blocks(list(pieces))
    accessor = B.BlockAccessor.for_block(merged)
    rows = accessor.to_rows()
    values = accessor.sort_key_values(key)
    order = sorted(range(len(rows)), key=values.__getitem__,
                   reverse=descending)
    return B.rows_to_block([rows[i] for i in order], merged)


def _merge_grouped(key, aggs, *pieces):
    """Reduce side of groupby: hash-partitioned rows → one row per
    group with finalized aggregates."""
    merged = B.concat_blocks(list(pieces))
    accessor = B.BlockAccessor.for_block(merged)
    groups: dict = {}
    for row in accessor.to_rows():
        group = key(row) if callable(key) else row[key]
        accs = groups.get(group)
        if accs is None:
            accs = [agg.init() for agg in aggs]
            groups[group] = accs
        for i, agg in enumerate(aggs):
            accs[i] = agg.accumulate(accs[i], agg.value_of(row))
    out = []
    key_name = key if isinstance(key, str) else "key"
    for group, accs in groups.items():
        row = {key_name: group}
        for agg, acc in zip(aggs, accs):
            row[agg.name] = agg.finalize(acc)
        out.append(row)
    return out


def _sample_keys(block, key, k: int, seed: int):
    accessor = B.BlockAccessor.for_block(block)
    values = accessor.sort_key_values(key)
    rng = random.Random(seed)
    if len(values) <= k:
        return list(values)
    return rng.sample(list(values), k)


def _block_rows(block) -> int:
    return B.BlockAccessor.for_block(block).num_rows()


def _slice_remote(block, start: int, end: int):
    return B.BlockAccessor.for_block(block).slice(start, end)


# ------------------------------------------------- streaming machinery

def _sizes(refs: list) -> list:
    """Best-effort per-ref payload sizes (None when pending/unknown) —
    feeds the byte budget.  Driver-owned refs answer from the local
    memory store, so this is in-process, not an RPC fan-out."""
    from ant_ray_tpu.api import global_worker  # noqa: PLC0415

    try:
        return global_worker.runtime.object_sizes(list(refs))
    except Exception:  # noqa: BLE001 — sizes are an optimization only
        return [None] * len(refs)


def _window_bytes(in_refs: list, known: dict) -> int:
    """Estimated bytes held by the window's input blocks.  ``known``
    memoizes resolved sizes (block payloads are immutable once ready),
    so only still-pending refs are re-queried; unknown sizes assume the
    average of the known ones (0 until anything is known)."""
    unknown = [r for r in in_refs if r.id not in known]
    if unknown:
        for ref, size in zip(unknown, _sizes(unknown)):
            if size is not None:
                known[ref.id] = size
    sizes = [known.get(r.id) for r in in_refs]
    resolved = [s for s in sizes if s]
    if not resolved:
        return 0
    avg = sum(resolved) // len(resolved)
    return sum(s if s else avg for s in sizes)


def _probe(out) -> object:
    """The ref whose completion signals a launched task finished (first
    return for num_returns=k tasks)."""
    return out[0] if isinstance(out, list) else out


def _windowed(upstream: Iterator, launch: Callable,
              tasks_cap: int | None = None,
              ref_of: Callable = lambda item: item) -> Iterator:
    """Ordered bounded-launch pipeline: apply ``launch`` to each
    upstream item with at most ``tasks_cap`` outstanding tasks and at
    most ``data_inflight_bytes`` estimated in-flight input bytes; the
    head task is awaited before its output is yielded, so demand
    propagates backwards.  Input items are dropped as their tasks are
    yielded — a consumed source block loses its driver ref and becomes
    freeable while later blocks still stream in.  ``ref_of`` extracts
    the input block ref from an item (for enumerated streams)."""
    art = _art()
    cfg = _cfg()
    tasks_cap = tasks_cap or cfg.data_inflight_tasks
    bytes_cap = cfg.data_inflight_bytes
    window: deque = deque()          # (out, in_ref)
    known_sizes: dict = {}           # ref.id -> bytes, sticky once known
    upstream = iter(upstream)
    exhausted = False
    while True:
        while not exhausted and len(window) < tasks_cap:
            if bytes_cap and window and \
                    _window_bytes([i for _, i in window],
                                  known_sizes) >= bytes_cap:
                break
            try:
                item = next(upstream)
            except StopIteration:
                exhausted = True
                break
            window.append((launch(item), ref_of(item)))
        if not window:
            return
        out, src = window.popleft()
        known_sizes.pop(getattr(src, "id", None), None)
        art.wait([_probe(out)], num_returns=1, timeout=600)
        yield out


def _merge_stream(rows: list, make_merge: Callable, k: int,
                  lookahead: int = 2) -> Iterator:
    """Lazy reduce phase: partition j's merge launches only when
    downstream demand reaches it (plus ``lookahead`` pipelined ahead);
    launched columns are nulled out of ``rows`` so merged partitions'
    pieces free while later columns still hold theirs."""
    launched: deque = deque()
    next_j = 0

    def _launch():
        nonlocal next_j
        column = [row[next_j] for row in rows]
        launched.append(make_merge(next_j, column))
        for row in rows:
            row[next_j] = None
        next_j += 1

    while next_j < k and len(launched) < lookahead:
        _launch()
    while launched:
        out = launched.popleft()
        if next_j < k:
            _launch()
        yield out


def _as_row(out) -> list:
    # num_returns=1 split tasks return a bare ref; widen to a 1-row.
    return out if isinstance(out, list) else [out]


def _collect_rows(upstream: Iterator, make_split: Callable,
                  ref_of: Callable = lambda item: item) -> list:
    """Windowed split phase: returns the piece-ref matrix (refs only —
    the pieces themselves live in the store and spill under pressure).
    Source refs are dropped as their splits complete."""
    return [_as_row(out)
            for out in _windowed(upstream, make_split, ref_of=ref_of)]


# ------------------------------------------------------------- stages

def _map_stage(upstream: Iterator, fused: L.FusedMap,
               in_flight: int) -> Iterator:
    """Ordered, bounded map over a ref stream (backpressure: at most
    ``in_flight`` tasks / ``data_inflight_bytes`` bytes outstanding;
    upstream pulled only when a slot frees)."""
    art = _art()
    apply_remote = art.remote(_apply_fused)
    yield from _windowed(upstream, lambda r: apply_remote.remote(fused, r),
                         tasks_cap=in_flight)


_store_capacity_cache: dict = {}


def _store_capacity() -> int | None:
    """Local node's shared-memory store capacity (cached per node
    address — clusters restart within one test process) — bounds the
    target partition size so a merge output can always fit."""
    try:
        from ant_ray_tpu.api import global_worker  # noqa: PLC0415

        runtime = global_worker.runtime
        addr = runtime.node_address
        if addr in _store_capacity_cache:
            return _store_capacity_cache[addr]
        # Reuse the runtime's live client pool — a fresh ClientPool
        # would leak one never-closed connection per node address.
        node = runtime._clients.get(addr)
        cap = node.call("GetStoreStats", {}, timeout=5)["capacity"]
        _store_capacity_cache[addr] = cap
        return cap
    except Exception:  # noqa: BLE001 — stats are an optimization only
        return None


def _pick_k(refs: list, requested: int | None) -> int:
    """Partition count: the caller's explicit block count, else
    total-bytes / target (size-aware repartitioning: target is
    data_target_block_bytes clamped to ⅛ of store capacity so merge
    outputs always fit the store), else the input block count."""
    if requested:
        return requested
    n = max(1, len(refs))
    sizes = [s for s in _sizes(refs) if s]
    if sizes:
        total = sum(sizes) * len(refs) // len(sizes)  # scale up unknowns
        target = max(1, _cfg().data_target_block_bytes)
        cap = _store_capacity()
        if cap:
            target = min(target, max(1, cap // 8))
        k = max(1, -(-total // target))
        return max(min(k, 4 * n), 1)
    return n


def _shuffle_stage(upstream: Iterator, requested_k: int | None,
                   mode: str, seed) -> Iterator:
    """Generic map-reduce shuffle: windowed split phase then lazy merge
    phase (pieces move store-to-store, never through the driver).
    mode="random" uses per-block split streams and a within-partition
    permutation at the merge — together a real two-stage uniform
    shuffle."""
    art = _art()
    if requested_k:
        k = requested_k
        refs: Iterator = upstream
    else:
        # Auto block count needs the input cardinality/size — collect
        # the *refs* (not blocks) first.
        collected = list(upstream)
        k = _pick_k(collected, None)
        refs = iter(collected)
    split_remote = art.remote(_split_block).options(num_returns=k)
    if mode == "random":
        if seed is None:  # derived streams must differ run to run
            seed = random.randrange(2**63)
        merge_shuffled = art.remote(_merge_shuffled)
        rows = _collect_rows(
            enumerate(refs),
            lambda item: split_remote.remote(
                item[1], k, mode, _stable_hash(("split", seed, item[0]))),
            ref_of=lambda item: item[1])
        yield from _merge_stream(
            rows, lambda j, col: merge_shuffled.remote(
                _stable_hash(("merge", seed, j)), *col), k)
        return
    rows = _collect_rows(refs,
                         lambda r: split_remote.remote(r, k, mode, seed))
    merge_remote = art.remote(_merge_blocks)
    yield from _merge_stream(rows,
                             lambda j, col: merge_remote.remote(*col), k)


def _sorted_stage(upstream: Iterator, key, descending: bool) -> Iterator:
    """Sample → range-partition → streaming merge (ref: the sort path
    of the streaming executor).  The sample pass streams over the
    upstream with bounded in-flight sample tasks; source refs must
    survive to the split pass (sort re-reads them), so sort's driver
    working set is the ref list plus one merge column of pieces —
    the blocks themselves spill under pressure."""
    art = _art()
    sample_remote = art.remote(_sample_keys)
    refs: list = []
    sample_refs: list = []
    cap = _cfg().data_inflight_tasks
    for ref in upstream:
        refs.append(ref)
        sample_refs.append(sample_remote.remote(ref, key, 8, len(refs)))
        if len(sample_refs) >= cap:
            # Bound concurrent sample tasks: wait out the one `cap`
            # launches back before admitting the next.
            art.wait([sample_refs[-cap]], num_returns=1, timeout=600)
    samples: list = []
    for chunk in art.get(sample_refs):
        samples.extend(chunk)
    samples.sort()
    k = _pick_k(refs, None)
    if len(samples) > 1 and k > 1:
        step = len(samples) / k
        boundaries = [samples[min(int(step * i), len(samples) - 1)]
                      for i in range(1, k)]
    else:
        boundaries = []
    k = len(boundaries) + 1
    split_remote = art.remote(_split_block_range).options(num_returns=k)
    rows = _collect_rows(
        iter(refs),
        lambda r: split_remote.remote(r, boundaries, key, descending))
    del refs  # sources consumed by the split pass — free/evictable
    merge_remote = art.remote(_merge_sorted)
    yield from _merge_stream(
        rows, lambda j, col: merge_remote.remote(key, descending, *col), k)


def _grouped_stage(upstream: Iterator, key, aggs) -> Iterator:
    art = _art()
    collected = list(upstream)
    k = _pick_k(collected, None)
    split_remote = art.remote(_split_block).options(num_returns=k)
    rows = _collect_rows(iter(collected),
                         lambda r: split_remote.remote(r, k, "hash", key))
    del collected
    merge_remote = art.remote(_merge_grouped)
    yield from _merge_stream(
        rows, lambda j, col: merge_remote.remote(key, tuple(aggs), *col), k)


def _limit_stage(upstream: Iterator, n: int) -> Iterator:
    art = _art()
    rows_remote = art.remote(_block_rows)
    slice_remote = art.remote(_slice_remote)
    remaining = n
    for ref in upstream:
        if remaining <= 0:
            return
        rows = art.get(rows_remote.remote(ref))
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield slice_remote.remote(ref, 0, remaining)
            remaining = 0


# ------------------------------------------------------------ executor

def execute(source: Callable[[], Iterator], operators: tuple,
            in_flight: int = DEFAULT_IN_FLIGHT) -> Iterator:
    """Stream block refs through the optimized operator chain.  Every
    stage (including the all-to-all ones) is a generator — demand
    propagates backwards from the consumer, and no stage materializes
    the dataset in the driver."""
    stream: Iterator = source()
    for op in L.optimize(operators):
        if isinstance(op, L.FusedMap):
            stream = _map_stage(stream, op, in_flight)
        elif isinstance(op, L.Repartition):
            stream = _shuffle_stage(stream, op.num_blocks, "even", None)
        elif isinstance(op, L.RandomShuffle):
            stream = _shuffle_stage(stream, op.num_blocks, "random",
                                    op.seed)
        elif isinstance(op, L.Sort):
            stream = _sorted_stage(stream, op.key, op.descending)
        elif isinstance(op, L.GroupByAggregate):
            stream = _grouped_stage(stream, op.key, op.aggs)
        elif isinstance(op, L.Limit):
            stream = _limit_stage(stream, op.n)
        else:  # pragma: no cover
            raise ValueError(f"unknown operator {op}")
    return stream
