"""Device-feed: prefetched, double-buffered host→device batch delivery.

The ingest gap this closes (T3, arxiv 2401.16677 — fine-grained overlap
of data movement with compute): ``iter_batches`` stops at host numpy
batches, so every training step pays collate + host→HBM transfer on the
critical path.  ``iter_device_batches`` moves both off it:

* a background **producer thread** pulls blocks, collates rows into
  contiguous fixed-shape arrays (the tail batch pads to ``batch_size``
  so a jitted step never recompiles), and issues **async**
  ``jax.device_put`` against the consumer's sharding — the host→HBM DMA
  for batch N+1 overlaps the step compute for batch N;
* a **bounded queue** (``prefetch_batches`` deep — 2 is classic double
  buffering) backpressures the producer so at most that many batches
  are in flight in HBM;
* the producer never blocks on transfer completion — the consumer's
  step dereferences the arrays, which is where XLA sequences the
  dependency.

``prefetch_batches=0`` is the synchronous baseline (collate + transfer
+ completion inline in the consumer's loop); it exists so the overlap
is observable — ``benchmarks/microbench.py``'s ``data_device_feed``
workload reports the consumer starve-fraction for both modes.

Every stage is timed into ``DeviceFeed.stats`` (block-wait, collate,
transfer-issue, consumer-starve), surfaced through
``DataIterator.stats()["device_feed"]``.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, Callable

import numpy as np

_END = ("end", None)


def _import_jax_or_none():
    try:
        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

        return import_jax()
    except Exception:  # noqa: BLE001 — host-only rigs feed numpy batches
        return None


def default_collate(batch) -> dict:
    """Numpy batch (dict of columns) → dict of contiguous numpy arrays.

    A list-block column of dict rows explodes into one array per key
    (the ``from_items([{...}])`` path).  Columns that stay object-dtype
    cannot form a fixed-shape device array — pass a ``collate_fn``."""
    if not isinstance(batch, dict):
        batch = {"value": batch}
    out: dict = {}
    for key, col in batch.items():
        arr = np.asarray(col)
        if arr.dtype == object:
            rows = list(col)
            if rows and all(isinstance(r, dict) for r in rows):
                for k in rows[0]:
                    sub = np.asarray([r[k] for r in rows])
                    if sub.dtype == object:
                        raise TypeError(
                            f"row key {k!r} is ragged/non-numeric; pass "
                            "a collate_fn that produces fixed-shape "
                            "arrays")
                    out[k] = np.ascontiguousarray(sub)
                continue
            raise TypeError(
                f"column {key!r} is not dense (dtype=object); pass a "
                "collate_fn that maps the numpy batch to fixed-shape "
                "arrays")
        out[key] = np.ascontiguousarray(arr)
    return out


def pad_to_batch(tree: dict, batch_size: int, pad_value=0):
    """Pad every array's leading dim to ``batch_size`` (returns
    ``(padded_tree, n_padding_rows)``).  Fixed shapes are the contract
    that keeps a jitted step at one compilation across the epoch."""
    n = None
    for leaf in tree.values():
        n = leaf.shape[0] if n is None else min(n, leaf.shape[0])
    if n is None or n >= batch_size:
        return tree, 0
    pad = batch_size - n
    out = {
        k: np.concatenate(
            [a, np.full((pad,) + a.shape[1:], pad_value, dtype=a.dtype)])
        for k, a in tree.items()
    }
    return out, pad


class DeviceFeed:
    """One epoch of device-batch delivery over a block stream.

    ``blocks_fn`` yields blocks (one pass); iterate the feed once.
    ``sharding`` may be a ``jax.sharding.Sharding`` / device, or a
    callable resolved lazily in the consuming process — called as
    ``sharding(rank, world)`` (falling back to no-args) so the trainer
    can forward per-worker shardings without shipping device handles.
    """

    def __init__(self, blocks_fn: Callable, *, batch_size: int,
                 prefetch_batches: int = 2, sharding: Any = None,
                 collate_fn: Callable | None = None,
                 drop_last: bool = False, pad_value=0,
                 rank: int = 0, world: int = 1):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive: {batch_size}")
        self._blocks_fn = blocks_fn
        self._batch_size = batch_size
        self._prefetch = max(0, int(prefetch_batches))
        self._sharding = sharding
        self._collate = collate_fn or default_collate
        self._drop_last = drop_last
        self._pad_value = pad_value
        self._rank = rank
        self._world = world
        self._jax = _import_jax_or_none()
        self.thread: threading.Thread | None = None
        self.stats: dict = {
            "batch_size": batch_size,
            "prefetch_batches": self._prefetch,
            "batches": 0,
            "tail_padded_rows": 0,
            "block_wait_s": 0.0,
            "collate_s": 0.0,
            "transfer_issue_s": 0.0,
            "consumer_starve_s": 0.0,
            "consumer_wall_s": 0.0,
            "consumer_starve_fraction": 0.0,
        }

    # ---- producer stages

    def _resolved_sharding(self):
        sharding = self._sharding
        if callable(sharding) and not hasattr(sharding, "device_set"):
            try:
                sharding = sharding(self._rank, self._world)
            except TypeError:
                sharding = sharding()
        return sharding

    def _timed_blocks(self):
        it = iter(self._blocks_fn())
        while True:
            t0 = time.perf_counter()
            try:
                block = next(it)
            except StopIteration:
                return
            self.stats["block_wait_s"] += time.perf_counter() - t0
            yield block

    def _host_batches(self):
        from ant_ray_tpu.data.block import batches_from_blocks  # noqa: PLC0415

        for batch in batches_from_blocks(self._timed_blocks(),
                                         self._batch_size, "numpy",
                                         self._drop_last):
            t0 = time.perf_counter()
            tree = self._collate(batch)
            tree, padded = pad_to_batch(tree, self._batch_size,
                                        self._pad_value)
            self.stats["collate_s"] += time.perf_counter() - t0
            self.stats["tail_padded_rows"] += padded
            yield tree

    def _to_device(self, tree, sharding):
        if self._jax is None:
            return tree            # host-only rig: numpy batches
        t0 = time.perf_counter()
        if sharding is None:
            out = self._jax.device_put(tree)
        else:
            out = self._jax.device_put(tree, sharding)
        # No block_until_ready: device_put is dispatched async; the DMA
        # runs while the consumer computes on the previous batch.
        self.stats["transfer_issue_s"] += time.perf_counter() - t0
        return out

    def _produce(self, q: _queue.Queue, stop: threading.Event,
                 sharding) -> None:
        try:
            for tree in self._host_batches():
                if stop.is_set():
                    return
                if not self._put(q, stop, ("batch",
                                           self._to_device(tree, sharding))):
                    return
            self._put(q, stop, _END)
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            self._put(q, stop, ("error", e))

    @staticmethod
    def _put(q: _queue.Queue, stop: threading.Event, item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    # ---- consumer

    def __iter__(self):
        sharding = self._resolved_sharding()
        wall0 = time.perf_counter()
        try:
            if self._prefetch == 0:
                yield from self._iter_sync(sharding)
            else:
                yield from self._iter_prefetched(sharding)
        finally:
            wall = time.perf_counter() - wall0
            self.stats["consumer_wall_s"] = wall
            self.stats["consumer_starve_fraction"] = (
                self.stats["consumer_starve_s"] / wall if wall > 0 else 0.0)

    def _iter_sync(self, sharding):
        """prefetch_batches=0: the blocking baseline — collate, transfer
        AND completion all on the consumer's critical path."""
        gen = self._host_batches()
        while True:
            t0 = time.perf_counter()
            try:
                tree = next(gen)
            except StopIteration:
                return
            dev = self._to_device(tree, sharding)
            if self._jax is not None:
                try:
                    self._jax.block_until_ready(dev)
                except Exception:  # noqa: BLE001 — older jax: tree-less
                    pass
            self.stats["consumer_starve_s"] += time.perf_counter() - t0
            self.stats["batches"] += 1
            yield dev

    def _iter_prefetched(self, sharding):
        q: _queue.Queue = _queue.Queue(maxsize=self._prefetch)
        stop = threading.Event()
        self.thread = threading.Thread(
            target=self._produce, args=(q, stop, sharding),
            daemon=True, name="device-feed-producer")
        self.thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                kind, payload = q.get()
                self.stats["consumer_starve_s"] += time.perf_counter() - t0
                if kind == "end":
                    return
                if kind == "error":
                    raise payload
                self.stats["batches"] += 1
                yield payload
        finally:
            # Early consumer exit (or normal end): release the producer
            # from a full queue and join it.
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            self.thread.join(timeout=5.0)
