"""Block model: the unit of data movement.

Two physical layouts behind one accessor interface (ref:
python/ray/data/block.py BlockAccessor — there list/Arrow/pandas, here
list and Arrow):

* **list blocks** — a plain Python list of rows (any objects).  The
  default for `from_items` / generic maps.
* **Arrow blocks** — a ``pyarrow.Table``.  Tabular datasources (csv /
  json / parquet) produce these; ``map_batches(format="numpy")`` gets
  zero-copy column views, which is the fast path into ``jnp.asarray``
  for TPU ingest.

Blocks live in the object store (serialization.py pickles an Arrow
table via its IPC buffers, which ride pickle-5 out-of-band, so a local
worker reads columns zero-copy from the shm arena).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import numpy as np


def _pa():
    import pyarrow  # noqa: PLC0415

    return pyarrow


class BlockAccessor:
    """Uniform view over one block."""

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        import pyarrow  # noqa: PLC0415

        if isinstance(block, pyarrow.Table):
            return ArrowBlockAccessor(block)
        if isinstance(block, list):
            return ListBlockAccessor(block)
        raise TypeError(f"not a block: {type(block)}")

    @staticmethod
    def batch_to_block(batch) -> Any:
        """A map_batches return value → block: dict of arrays becomes an
        Arrow table, a list stays a list block."""
        if isinstance(batch, dict):
            pa = _pa()
            return pa.table({
                k: (pa.array(np.asarray(v).tolist())
                    if getattr(np.asarray(v), "ndim", 1) > 1
                    else pa.array(np.asarray(v)))
                for k, v in batch.items()})
        return list(batch)

    # ---- required surface

    def num_rows(self) -> int:
        raise NotImplementedError

    def to_rows(self) -> list:
        raise NotImplementedError

    def to_batch(self, batch_format: str = "default"):
        """"default": rows for list blocks / dict-of-numpy for Arrow.
        "numpy": dict of numpy arrays.  "rows": list of rows (dicts for
        Arrow)."""
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Any:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def sort_key_values(self, key) -> list:
        """Values used for range-partitioned sort."""
        raise NotImplementedError


class ListBlockAccessor(BlockAccessor):
    def __init__(self, block: list):
        self._block = block

    def num_rows(self) -> int:
        return len(self._block)

    def to_rows(self) -> list:
        return self._block

    def to_batch(self, batch_format: str = "default"):
        if batch_format == "numpy":
            return {"value": np.asarray(self._block)}
        return self._block

    def slice(self, start: int, end: int) -> list:
        return self._block[start:end]

    def size_bytes(self) -> int:
        import sys  # noqa: PLC0415

        return sum(sys.getsizeof(x) for x in self._block)

    def sort_key_values(self, key) -> list:
        if key is None:
            return self._block
        if callable(key):
            return [key(x) for x in self._block]
        return [x[key] for x in self._block]


class ArrowBlockAccessor(BlockAccessor):
    def __init__(self, table):
        self._table = table

    def num_rows(self) -> int:
        return self._table.num_rows

    def to_rows(self) -> list:
        return self._table.to_pylist()

    def to_batch(self, batch_format: str = "default"):
        if batch_format == "rows":
            return self._table.to_pylist()
        # default / numpy: dict of numpy column arrays (zero-copy when
        # the type allows).
        return {name: self._table.column(name).to_numpy(
                    zero_copy_only=False)
                for name in self._table.column_names}

    def slice(self, start: int, end: int):
        return self._table.slice(start, end - start)

    def size_bytes(self) -> int:
        return self._table.nbytes

    def sort_key_values(self, key) -> list:
        if callable(key):
            return [key(row) for row in self._table.to_pylist()]
        return self._table.column(key).to_pylist()


def concat_blocks(blocks: list):
    """Concatenate blocks into one.

    Same-kind inputs keep their kind.  Mixed list/Arrow inputs promote
    list blocks of dict rows to Arrow; if any list block holds non-dict
    rows (from_pylist needs mappings) everything degrades to one list
    block instead."""
    if not blocks:
        return []
    if all(isinstance(b, list) for b in blocks):
        out: list = []
        for b in blocks:
            out.extend(b)
        return out
    nonempty = [b for b in blocks if not isinstance(b, list) or b]
    if any(isinstance(b, list) and not all(isinstance(r, dict) for r in b)
           for b in nonempty):
        out = []
        for b in nonempty:
            out.extend(BlockAccessor.for_block(b).to_rows())
        return out
    pa = _pa()
    tables = [b if not isinstance(b, list) else
              pa.Table.from_pylist(b) for b in nonempty]
    if not tables:
        return []
    return pa.concat_tables(tables, promote_options="default")


def batches_from_blocks(blocks: Iterable, batch_size: int,
                        batch_format: str = "default",
                        drop_last: bool = False):
    """Re-batch a block stream into fixed-size batches.  Batches
    assemble by block slice + concat, never round-tripping rows through
    Python, so Arrow dtypes survive (this is the TPU ingest path:
    batch_format="numpy" → dict of numpy columns → jnp.asarray).
    Shared by Dataset.iter_batches and every DataIterator."""
    pending: list = []     # [accessor, start offset] pieces
    pending_rows = 0
    for block in blocks:
        accessor = BlockAccessor.for_block(block)
        if accessor.num_rows() == 0:
            continue
        pending.append([accessor, 0])
        pending_rows += accessor.num_rows()
        while pending_rows >= batch_size:
            yield _assemble_batch(pending, batch_size, batch_format)
            pending_rows -= batch_size
    if pending_rows and not drop_last:
        yield _assemble_batch(pending, pending_rows, batch_format)


def _assemble_batch(pending: list, n: int, batch_format: str):
    pieces = []
    taken = 0
    while taken < n:
        accessor, start = pending[0]
        available = accessor.num_rows() - start
        use = min(available, n - taken)
        pieces.append(accessor.slice(start, start + use))
        taken += use
        if use == available:
            pending.pop(0)
        else:
            pending[0][1] = start + use
    batch_block = concat_blocks(pieces)
    if batch_format == "default" and isinstance(batch_block, list):
        return batch_block
    return BlockAccessor.for_block(batch_block).to_batch(
        "numpy" if batch_format in ("default", "numpy") else batch_format)


def rows_to_block(rows: list, like) -> Any:
    """Rebuild a block of the same kind as ``like`` from rows.

    The schema is inferred from the rows (a map may change columns
    entirely); ``like``'s schema is only kept for empty row lists,
    where there is nothing to infer from."""
    import pyarrow  # noqa: PLC0415

    if isinstance(like, pyarrow.Table):
        if not rows:
            return like.schema.empty_table()
        return pyarrow.Table.from_pylist(rows)
    return list(rows)


def map_rows(block, fn: Callable[[Any], Any]):
    """Apply a per-row fn; list blocks stay lists (fn may change row
    type arbitrarily), Arrow blocks rebuild from dict rows when the fn
    returns dicts, else degrade to a list block."""
    accessor = BlockAccessor.for_block(block)
    rows = [fn(row) for row in accessor.to_rows()]
    if not isinstance(block, list) and rows and \
            all(isinstance(r, dict) for r in rows):
        return rows_to_block(rows, block)
    return rows


def filter_rows(block, fn: Callable[[Any], bool]):
    import pyarrow  # noqa: PLC0415

    if isinstance(block, pyarrow.Table):
        mask = [bool(fn(row)) for row in block.to_pylist()]
        return block.filter(pyarrow.array(mask))
    return [x for x in block if fn(x)]


def flat_map_rows(block, fn: Callable[[Any], Iterable]):
    accessor = BlockAccessor.for_block(block)
    rows = [y for x in accessor.to_rows() for y in fn(x)]
    if not isinstance(block, list) and rows and \
            all(isinstance(r, dict) for r in rows):
        return rows_to_block(rows, block)
    return rows
