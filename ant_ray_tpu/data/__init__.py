"""Streaming datasets (ref capability: ray.data — lazy logical plan,
block-parallel execution, streaming iteration)."""

from ant_ray_tpu.data.dataset import Dataset, from_items, from_numpy, range_

range = range_  # noqa: A001 — mirrors ray.data.range

__all__ = ["Dataset", "from_items", "from_numpy", "range"]
