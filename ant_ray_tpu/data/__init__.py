"""Streaming datasets (ref capability: ray.data — logical plan with
operator fusion, Arrow/list blocks, pull-based streaming execution,
map-reduce shuffles, datasources)."""

from ant_ray_tpu.data.aggregate import Count, Max, Mean, Min, Sum
from ant_ray_tpu.data.dataset import (
    Dataset,
    GroupedData,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range_,
    read_csv,
    read_datasource,
    read_jsonl,
    read_parquet,
)
from ant_ray_tpu.data.datasource import Datasource, ReadTask
from ant_ray_tpu.data.iterator import DataIterator

range = range_  # noqa: A001 — mirrors ray.data.range

__all__ = [
    "Count",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "Max",
    "Mean",
    "Min",
    "ReadTask",
    "Sum",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_csv",
    "read_datasource",
    "read_jsonl",
    "read_parquet",
]
