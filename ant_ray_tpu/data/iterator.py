"""DataIterator + streaming split: coordinated per-consumer streams.

Mirror of the reference's ``Dataset.streaming_split``
(ref: python/ray/data/dataset.py:1881) and ``DataIterator``
(ref: python/ray/data/iterator.py:55), redesigned for this runtime:

* ``Dataset.streaming_split(n)`` spawns ONE ``_SplitCoordinator`` actor
  holding the logical plan.  Each epoch, the coordinator drives the
  streaming executor once in a background thread and fans block *refs*
  out to ``n`` bounded per-consumer queues — blocks themselves move
  store-to-store and spill under pressure; the coordinator only ever
  holds a handful of refs (queue cap + one held-back tail block per
  consumer), so the footprint is bounded no matter the dataset size.
* Epochs are coordinated: every consumer's ``iter_batches`` call hits a
  barrier (``start_epoch``) so a new pass over the data starts only
  when all ranks finished the previous one — the semantics SPMD
  training needs (ref: StreamSplitDataIterator's coordinator,
  python/ray/data/_internal/execution/operators/output_splitter.py).
* ``equal=True`` guarantees every consumer yields EXACTLY the same row
  count per epoch (collective lockstep must not deadlock on a short
  rank): blocks dispatch greedily to the consumer with the fewest rows
  (in-stream imbalance ≤ one block), the tail block per consumer is
  held back, and at stream end tails are sliced so all match the
  minimum; a stream with fewer blocks than consumers splits tail
  blocks further so nobody starves.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Iterator

from ant_ray_tpu.data.block import batches_from_blocks

logger = logging.getLogger(__name__)

# Block refs buffered per output split: the producer thread stalls when
# a consumer's queue is full, which stalls the executor's pull, which
# stops launching read/map tasks — end-to-end backpressure.
_QUEUE_CAP = 2
# How long a rank parked at a retry barrier waits after a PREVIOUS
# epoch failed with no sign of the other ranks retrying, before the
# failure is surfaced to it too (epoch-scoped errors keep the stale
# failure out of a genuine retry; this grace keeps a gang that is NOT
# retrying from parking the early rank forever).
_BARRIER_GRACE_S = 30.0


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


class DataIterator:
    """One consumer's stream over a dataset (ref:
    python/ray/data/iterator.py:55).  Each ``iter_batches`` /
    ``iter_rows`` call is one full pass (one epoch); concrete
    subclasses supply the block-ref stream."""

    # Defaults for iter_device_batches, set via configure_device_feed
    # (the trainer forwards DataConfig.device_feed per rank through it).
    _device_feed_defaults: dict | None = None
    _last_device_feed = None

    def _iter_block_refs(self) -> Iterator:
        raise NotImplementedError

    def _iter_blocks(self) -> Iterator:
        art = _art()
        for ref in self._iter_block_refs():
            yield art.get(ref)

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "default",
                     drop_last: bool = False) -> Iterator:
        yield from batches_from_blocks(self._iter_blocks(), batch_size,
                                       batch_format, drop_last)

    def iter_rows(self) -> Iterator:
        from ant_ray_tpu.data.block import BlockAccessor  # noqa: PLC0415

        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).to_rows()

    def iter_device_batches(self, batch_size: int | None = None,
                            prefetch_batches: int | None = None,
                            sharding=None, collate_fn=None, *,
                            drop_last: bool | None = None,
                            pad_value=None) -> Iterator:
        """One epoch of prefetched, double-buffered DEVICE batches.

        A background producer thread pulls blocks, collates rows into
        contiguous fixed-shape arrays (the tail batch pads to
        ``batch_size`` so a jitted step never recompiles), and issues
        async ``jax.device_put`` against ``sharding`` into a bounded
        queue — the host→HBM transfer for batch N+1 overlaps the step
        compute for batch N.  ``prefetch_batches=0`` is the blocking
        baseline (transfer on the critical path).  ``sharding`` may be
        a jax Sharding/device or a callable ``(rank, world) ->
        sharding`` resolved lazily in the consuming process.  Per-stage
        timings land in ``stats()["device_feed"]``.
        """
        from ant_ray_tpu.data.device_feed import DeviceFeed  # noqa: PLC0415

        d = self._device_feed_defaults or {}
        feed = DeviceFeed(
            self._iter_blocks,
            batch_size=(batch_size if batch_size is not None
                        else d.get("batch_size", 256)),
            prefetch_batches=(prefetch_batches
                              if prefetch_batches is not None
                              else d.get("prefetch_batches", 2)),
            sharding=sharding if sharding is not None else d.get("sharding"),
            collate_fn=(collate_fn if collate_fn is not None
                        else d.get("collate_fn")),
            drop_last=(drop_last if drop_last is not None
                       else d.get("drop_last", False)),
            pad_value=(pad_value if pad_value is not None
                       else d.get("pad_value", 0)),
            rank=d.get("rank", getattr(self, "_rank", 0)),
            world=d.get("world", getattr(self, "_world", 1)),
        )
        self._last_device_feed = feed
        return iter(feed)

    def configure_device_feed(self, **defaults) -> "DataIterator":
        """Set defaults for :meth:`iter_device_batches` (keys:
        batch_size, prefetch_batches, sharding, collate_fn, drop_last,
        pad_value, rank, world).  The train controller calls this per
        rank from ``DataConfig.device_feed``; explicit call-site
        arguments still win."""
        merged = dict(self._device_feed_defaults or {})
        merged.update(defaults)
        self._device_feed_defaults = merged
        return self

    def stats(self) -> dict:
        """Observability surface: per-stage timings of the most recent
        (possibly still-running) device feed under ``"device_feed"``
        (block-wait, collate, transfer-issue, consumer-starve)."""
        out: dict = {}
        feed = self._last_device_feed
        if feed is not None:
            out["device_feed"] = dict(feed.stats)
        return out

    def __getstate__(self):
        # Iterators ship to workers; a live feed (thread handle) does
        # not survive pickling and never needs to.
        state = dict(self.__dict__)
        state.pop("_last_device_feed", None)
        return state

    def materialize(self):
        """Drain one epoch into a plain Dataset (refs, not rows)."""
        from ant_ray_tpu.data.dataset import Dataset  # noqa: PLC0415

        return Dataset(list(self._iter_block_refs()))


class PlanIterator(DataIterator):
    """Full-dataset iterator: every pass re-executes the plan (the
    non-split path — e.g. a validation set broadcast to all workers)."""

    def __init__(self, dataset):
        self._ds = dataset

    def _iter_block_refs(self) -> Iterator:
        return self._ds._iter_result_refs()

    def __repr__(self):
        return f"PlanIterator({self._ds!r})"


class StreamSplitDataIterator(DataIterator):
    """Consumer ``rank`` of an n-way coordinated streaming split.

    Serializable (actor handle + ints) — the trainer ships one per
    worker; ``train.get_dataset_shard`` hands it to the loop."""

    def __init__(self, coordinator, rank: int, world: int, name: str = ""):
        self._coord = coordinator
        self._rank = rank
        self._world = world
        self._name = name
        self._epoch = 0

    def _iter_block_refs(self) -> Iterator:
        art = _art()
        epoch = self._epoch
        self._epoch += 1
        # Barrier: a new pass starts only when every rank asked for it.
        art.get(self._coord.start_epoch.remote(self._rank, epoch))
        # One-deep pipeline: the request for block k+1 is in flight
        # while the consumer processes block k, hiding the coordinator
        # round-trip (mirror of the reference iterator's prefetch).
        pending = self._coord.next_block.remote(self._rank, epoch)
        while True:
            kind, payload = art.get(pending)
            if kind == "block":
                pending = self._coord.next_block.remote(self._rank, epoch)
                yield payload
            elif kind == "end":
                return
            else:
                raise RuntimeError(
                    f"streaming split '{self._name}' failed: {payload}")

    def stats(self) -> dict:
        out = _art().get(self._coord.stats.remote())
        out.update(DataIterator.stats(self))   # adds "device_feed"
        return out

    def __repr__(self):
        return (f"StreamSplitDataIterator(name={self._name!r}, "
                f"rank={self._rank}/{self._world})")


class _Aborted(Exception):
    """Producer thread raced a coordinator teardown/new generation."""


class _SplitCoordinator:
    """Actor coordinating one Dataset stream over ``n`` consumers.

    Runs with max_concurrency > n: every rank parks a blocking
    ``next_block`` call here while the producer thread feeds queues.
    """

    def __init__(self, dataset, n: int, equal: bool, name: str = ""):
        self._ds = dataset
        self._n = n
        self._equal = equal
        self._name = name
        self._cv = threading.Condition()
        self._queues: list[deque] = [deque() for _ in range(n)]
        self._epoch = -1               # epoch currently running/finished
        self._arrived: set = set()     # (epoch, rank) barrier arrivals
        self._done = False             # current epoch's stream exhausted
        # Errors are scoped (epoch, repr): a retried epoch must never
        # see the previous epoch's failure (a rank arriving early at
        # the new barrier would otherwise re-raise the stale error and
        # desync the gang forever).
        self._error: "tuple[int, str] | None" = None
        self._rows_out = [0] * n       # last finished epoch's row counts
        self._epochs_finished = 0

    # ---- consumer API

    def start_epoch(self, rank: int, epoch: int) -> bool:
        with self._cv:
            if epoch <= self._epoch:
                return True            # already running (late re-entry)
            self._arrived.add((epoch, rank))
            # Wake the producer: a rank parked at a FUTURE barrier has
            # abandoned the current epoch (broke out of its batch loop)
            # and must not be pushed to (its full queue would deadlock
            # the stream for everyone else).
            self._cv.notify_all()
            if all((epoch, r) in self._arrived for r in range(self._n)):
                self._arrived = {p for p in self._arrived
                                 if p[0] > epoch}
                self._epoch = epoch
                self._done = False
                self._error = None
                for q in self._queues:
                    q.clear()
                threading.Thread(target=self._run_epoch, args=(epoch,),
                                 daemon=True).start()
                self._cv.notify_all()
            else:
                grace_deadline = None
                seen_arrivals = len(self._arrived)
                while not (self._epoch >= epoch
                           or self._epoch_error(epoch) is not None):
                    self._cv.wait(timeout=1.0)
                    if len(self._arrived) != seen_arrivals:
                        seen_arrivals = len(self._arrived)
                        grace_deadline = None   # gang is arriving
                    prev = self._error
                    if (prev is not None and prev[0] < epoch
                            and self._epoch < epoch):
                        # A previous epoch failed and this barrier is
                        # not filling: the other ranks may never retry.
                        now = time.monotonic()
                        if grace_deadline is None:
                            grace_deadline = now + _BARRIER_GRACE_S
                        elif now >= grace_deadline:
                            raise RuntimeError(
                                f"streaming split '{self._name}' "
                                f"barrier for epoch {epoch} abandoned: "
                                f"epoch {prev[0]} failed ({prev[1]}) "
                                "and the other consumers did not retry")
            return True

    def _epoch_error(self, epoch: int) -> str | None:
        """The recorded error IF it belongs to ``epoch`` (errors are
        (epoch, repr) pairs; other epochs' failures are invisible)."""
        if self._error is not None and self._error[0] == epoch:
            return self._error[1]
        return None

    def next_block(self, rank: int, epoch: int):
        with self._cv:
            while True:
                err = self._epoch_error(epoch)
                if err is not None:
                    return ("error", err)
                if epoch < self._epoch:
                    # A newer epoch started (this consumer was resliced
                    # away mid-stream) — its old stream is over.
                    return ("end", None)
                if self._queues[rank]:
                    ref = self._queues[rank].popleft()
                    self._cv.notify_all()     # queue room → wake producer
                    # Handing the ref to the consumer drops this actor's
                    # last strong reference (the queue slot); a grace
                    # pin bridges to the consumer's borrow registration,
                    # like device_objects does for the same hand-off.
                    self._grace_pin(ref)
                    return ("block", ref)
                if self._done:
                    return ("end", None)
                self._cv.wait(timeout=1.0)

    @staticmethod
    def _grace_pin(ref) -> None:
        try:
            from ant_ray_tpu.api import global_worker  # noqa: PLC0415

            global_worker.runtime.pin_for_grace(ref)
        except Exception:  # noqa: BLE001 — pin is belt-and-braces only
            pass

    def stats(self) -> dict:
        with self._cv:
            return {"name": self._name, "splits": self._n,
                    "equal": self._equal,
                    "epochs_finished": self._epochs_finished,
                    "rows_per_split": list(self._rows_out)}

    # ---- producer (one thread per epoch)

    def _run_epoch(self, epoch: int) -> None:
        try:
            if self._equal:
                self._produce_equal(epoch)
            else:
                self._produce_any(epoch)
            with self._cv:
                if self._epoch == epoch:
                    self._done = True
                    self._epochs_finished += 1
                    self._cv.notify_all()
        except _Aborted:
            pass
        except Exception as e:  # noqa: BLE001 — surfaced to consumers
            logger.exception("streaming split '%s' epoch %d failed",
                             self._name, epoch)
            with self._cv:
                # Only poison the epoch that actually failed; a late
                # failure from a superseded epoch's thread must not
                # leak into the one now running.
                if self._epoch == epoch:
                    self._error = (epoch, repr(e))
                    self._cv.notify_all()

    def _abandoned(self, rank: int, epoch: int) -> bool:
        return any(r == rank and e > epoch for e, r in self._arrived)

    def _push(self, rank: int, ref, epoch: int) -> None:
        with self._cv:
            self._cv.wait_for(
                lambda: len(self._queues[rank]) < _QUEUE_CAP
                or self._abandoned(rank, epoch)
                or self._epoch != epoch
                or self._epoch_error(epoch) is not None)
            if self._epoch != epoch or self._epoch_error(epoch) is not None:
                raise _Aborted
            if self._abandoned(rank, epoch):
                return                 # consumer left this epoch; drop
            self._queues[rank].append(ref)
            self._cv.notify_all()

    def _shortest_queue(self, epoch: int) -> int:
        """Rank with the most queue room (ties → lowest rank); waits
        until someone has room."""
        with self._cv:
            self._cv.wait_for(
                lambda: any(len(q) < _QUEUE_CAP for q in self._queues)
                or self._epoch != epoch
                or self._epoch_error(epoch) is not None)
            if self._epoch != epoch or self._epoch_error(epoch) is not None:
                raise _Aborted
            return min(range(self._n),
                       key=lambda r: (len(self._queues[r]), r))

    def _produce_any(self, epoch: int) -> None:
        """equal=False: dynamic dispatch to whichever consumer has queue
        room — natural load balancing, no row counting."""
        for ref in self._ds._iter_result_refs():
            self._push(self._shortest_queue(epoch), ref, epoch)

    def _produce_equal(self, epoch: int) -> None:
        """equal=True: greedy min-rows dispatch with one held-back tail
        block per consumer, trimmed at stream end so every consumer
        gets exactly min-rows rows."""
        art = _art()
        from ant_ray_tpu.data.executor import (  # noqa: PLC0415
            _block_rows,
            _slice_remote,
        )

        rows_remote = art.remote(_block_rows)
        slice_remote = art.remote(_slice_remote)
        rows = [0] * self._n           # dispatched rows incl. held tail
        held: list = [None] * self._n  # held-back tail ref per rank
        held_rows = [0] * self._n

        def dispatch(ref, cnt: int) -> None:
            if cnt == 0:
                return
            target = min(range(self._n), key=lambda r: (rows[r], r))
            rows[target] += cnt
            prev, held[target] = held[target], ref
            held_rows[target] = cnt
            if prev is not None:
                self._push(target, prev, epoch)

        # Row counts pipeline a few blocks ahead of dispatch — one
        # serial submit+get round-trip per block would cap the stream
        # at the scheduler RTT.
        counting: deque = deque()      # (ref, count_ref)
        for ref in self._ds._iter_result_refs():
            counting.append((ref, rows_remote.remote(ref)))
            if len(counting) >= 4:
                head, cnt_ref = counting.popleft()
                dispatch(head, art.get(cnt_ref))
        while counting:
            head, cnt_ref = counting.popleft()
            dispatch(head, art.get(cnt_ref))
        # Starved consumers (stream had fewer blocks than splits): split
        # the largest tail in two until everyone holds something.
        while min(rows) == 0 and max(held_rows) > 1:
            donor = max(range(self._n), key=lambda r: held_rows[r])
            taker = rows.index(0)
            half = held_rows[donor] // 2
            hi = slice_remote.remote(held[donor], half, held_rows[donor])
            lo = slice_remote.remote(held[donor], 0, half)
            held[taker], held_rows[taker] = hi, held_rows[donor] - half
            rows[taker] = held_rows[taker]
            rows[donor] -= held_rows[taker]
            held[donor], held_rows[donor] = lo, half
        # Trim every tail to the global minimum.  Greedy dispatch keeps
        # each rank's excess ≤ its tail block's rows, so slicing the
        # tail alone suffices.
        target_rows = min(rows)
        for r in range(self._n):
            excess = rows[r] - target_rows
            if held[r] is None:
                continue
            if excess >= held_rows[r]:
                rows[r] -= held_rows[r]
                continue               # drop the whole tail
            if excess > 0:
                held[r] = slice_remote.remote(
                    held[r], 0, held_rows[r] - excess)
                rows[r] -= excess
            self._push(r, held[r], epoch)
        with self._cv:
            self._rows_out = rows


def make_streaming_split(dataset, n: int, equal: bool = False,
                         name: str = "") -> list[StreamSplitDataIterator]:
    """Build the coordinator actor + n consumer iterators (the body of
    Dataset.streaming_split; also called directly by the trainer)."""
    art = _art()
    coord = art.remote(_SplitCoordinator).options(
        # Every rank parks a call here while the producer runs; leave
        # headroom for stats/barrier calls on top.
        max_concurrency=2 * n + 4, num_cpus=0,
    ).remote(dataset, n, equal, name)
    return [StreamSplitDataIterator(coord, r, n, name) for r in range(n)]
