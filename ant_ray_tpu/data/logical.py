"""Logical plan + optimizer.

The reference splits Dataset into a logical operator DAG, an optimizer
(fusion), and a physical streaming topology (ref:
python/ray/data/_internal/logical/ + execution/streaming_executor.py).
Here the plan is a linear chain (datasets are linear pipelines; joins
arrive as Zip/Union sources), the optimizer fuses runs of one-to-one
row transforms into a single task per block, and executor.py streams
blocks through the fused stages.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable


# One-to-one row/batch transforms — candidates for fusion.
@dataclass(frozen=True)
class MapRows:
    fn: Callable

@dataclass(frozen=True)
class FilterRows:
    fn: Callable

@dataclass(frozen=True)
class FlatMapRows:
    fn: Callable

@dataclass(frozen=True)
class MapBatches:
    fn: Callable
    batch_size: int | None
    batch_format: str


# All-to-all barriers.
@dataclass(frozen=True)
class Repartition:
    num_blocks: int

@dataclass(frozen=True)
class RandomShuffle:
    seed: int | None
    num_blocks: int | None = None

@dataclass(frozen=True)
class Sort:
    key: Any
    descending: bool = False

@dataclass(frozen=True)
class GroupByAggregate:
    key: Any
    aggs: tuple          # of aggregate.AggregateFn


# Misc.
@dataclass(frozen=True)
class Limit:
    n: int


@dataclass(frozen=True)
class FusedMap:
    """Optimizer output: a run of one-to-one ops as one block fn."""

    fns: tuple  # of (kind, op) pairs

    def __call__(self, block):
        from ant_ray_tpu.data import block as B  # noqa: PLC0415

        for kind, op in self.fns:
            if kind == "map":
                block = B.map_rows(block, op.fn)
            elif kind == "filter":
                block = B.filter_rows(block, op.fn)
            elif kind == "flat_map":
                block = B.flat_map_rows(block, op.fn)
            elif kind == "map_batches":
                block = _apply_map_batches(block, op)
            else:  # pragma: no cover
                raise ValueError(kind)
        return block


def _apply_map_batches(block, op: MapBatches):
    from ant_ray_tpu.data import block as B  # noqa: PLC0415

    accessor = B.BlockAccessor.for_block(block)
    n = accessor.num_rows()
    size = op.batch_size or max(n, 1)
    pieces = []
    for start in range(0, max(n, 1), size):
        piece = accessor.slice(start, min(start + size, n))
        batch = B.BlockAccessor.for_block(piece).to_batch(op.batch_format)
        out = op.fn(batch)
        pieces.append(B.BlockAccessor.batch_to_block(out))
        if n == 0:
            break
    return B.concat_blocks(pieces)


_ONE_TO_ONE = {MapRows: "map", FilterRows: "filter",
               FlatMapRows: "flat_map", MapBatches: "map_batches"}


def optimize(operators: tuple) -> tuple:
    """Fuse adjacent one-to-one operators (the reference's
    OperatorFusionRule)."""
    fused: list = []
    run: list = []
    for op in operators:
        kind = _ONE_TO_ONE.get(type(op))
        if kind is not None:
            run.append((kind, op))
            continue
        if run:
            fused.append(FusedMap(tuple(run)))
            run = []
        fused.append(op)
    if run:
        fused.append(FusedMap(tuple(run)))
    return tuple(fused)
