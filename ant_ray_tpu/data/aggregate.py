"""Aggregations for groupby / global aggregates (ref:
python/ray/data/aggregate.py — AggregateFn with init/accumulate/merge/
finalize, the classic combiner contract)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class AggregateFn:
    name: str
    init: Callable[[], Any]
    accumulate: Callable[[Any, Any], Any]     # (acc, row_value) -> acc
    merge: Callable[[Any, Any], Any]          # (acc, acc) -> acc
    finalize: Callable[[Any], Any] = staticmethod(lambda a: a)
    on: Any = None                            # column / fn the value comes from

    def value_of(self, row):
        if self.on is None:
            return row
        if callable(self.on):
            return self.on(row)
        return row[self.on]


def Count() -> AggregateFn:
    return AggregateFn(
        name="count", init=lambda: 0,
        accumulate=lambda a, _v: a + 1,
        merge=lambda a, b: a + b)


def Sum(on=None) -> AggregateFn:
    return AggregateFn(
        name=f"sum({on})" if on is not None else "sum",
        init=lambda: 0, accumulate=lambda a, v: a + v,
        merge=lambda a, b: a + b, on=on)


def Min(on=None) -> AggregateFn:
    return AggregateFn(
        name=f"min({on})" if on is not None else "min",
        init=lambda: None,
        accumulate=lambda a, v: v if a is None else min(a, v),
        merge=lambda a, b: b if a is None else (a if b is None
                                                else min(a, b)),
        on=on)


def Max(on=None) -> AggregateFn:
    return AggregateFn(
        name=f"max({on})" if on is not None else "max",
        init=lambda: None,
        accumulate=lambda a, v: v if a is None else max(a, v),
        merge=lambda a, b: b if a is None else (a if b is None
                                                else max(a, b)),
        on=on)


def Mean(on=None) -> AggregateFn:
    return AggregateFn(
        name=f"mean({on})" if on is not None else "mean",
        init=lambda: (0, 0),
        accumulate=lambda a, v: (a[0] + v, a[1] + 1),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda a: a[0] / a[1] if a[1] else None,
        on=on)
