"""Dataset: the public handle over a logical plan.

Architecture (mirror of the reference, SURVEY §2.4 Data): a Dataset is
(source, logical operator chain).  Transforms append logical operators
(logical.py); consumption optimizes the chain (one-to-one runs fuse
into one task per block) and streams block refs through the pull-based
executor (executor.py) with bounded in-flight tasks, so datasets larger
than memory flow.  Blocks are list or Arrow blocks (block.py);
all-to-all ops (shuffle / sort / groupby / repartition) run as
map-reduce task graphs, never materializing in the driver.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator

from ant_ray_tpu.data import aggregate as agg
from ant_ray_tpu.data import logical as L
from ant_ray_tpu.data.block import BlockAccessor
from ant_ray_tpu.data.datasource import (
    CSVDatasource,
    Datasource,
    JSONLDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    write_jsonl_block,
    write_parquet_block,
)
from ant_ray_tpu.data.executor import DEFAULT_IN_FLIGHT, execute

DEFAULT_PARALLELISM = 8


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


def _run_read_task(task: ReadTask):
    return task.fn()


def _block_schema(block):
    return None if isinstance(block, list) else block.schema


class Dataset:
    def __init__(self, block_refs: list | None = None,
                 operators: tuple = (),
                 read_tasks: list | None = None):
        self._block_refs = list(block_refs or [])
        self._read_tasks = list(read_tasks or [])
        self._operators = tuple(operators)

    # ---------------------------------------------------------- source

    def _source(self) -> Iterator:
        """Iterator of input block refs; read tasks launch lazily with
        the executor's window providing backpressure."""
        if self._read_tasks:
            art = _art()
            run_read = art.remote(_run_read_task)
            for task in self._read_tasks:
                yield run_read.remote(task)
        yield from self._block_refs

    def _with(self, op) -> "Dataset":
        return Dataset(self._block_refs, self._operators + (op,),
                       self._read_tasks)

    # ------------------------------------------------------- transforms

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with(L.MapRows(fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with(L.FilterRows(fn))

    def flat_map(self, fn: Callable[[Any], Iterable]) -> "Dataset":
        return self._with(L.FlatMapRows(fn))

    def map_batches(self, fn: Callable, batch_size: int | None = None,
                    batch_format: str = "default") -> "Dataset":
        return self._with(L.MapBatches(fn, batch_size, batch_format))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(n))

    # ------------------------------------------------------- all-to-all

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(L.Repartition(num_blocks))

    def random_shuffle(self, seed: int | None = None) -> "Dataset":
        return self._with(L.RandomShuffle(seed))

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        return self._with(L.Sort(key, descending))

    def groupby(self, key) -> "GroupedData":
        return GroupedData(self, key)

    # ---------------------------------------------------- set operations

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (each side materializes its own plan)."""
        datasets = (self,) + others
        refs: list = []
        for ds in datasets:
            refs.extend(ds.materialize()._block_refs)
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned zip into (row_a, row_b) tuples."""
        a = self.take_all()
        b = other.take_all()
        if len(a) != len(b):
            raise ValueError(
                f"zip needs equal row counts, got {len(a)} vs {len(b)}")
        return from_items(list(builtins.zip(a, b)))

    # -------------------------------------------------------- execution

    def _iter_result_refs(self, in_flight: int = DEFAULT_IN_FLIGHT
                          ) -> Iterator:
        return execute(self._source, self._operators, in_flight)

    def _iter_result_blocks(self, in_flight: int = DEFAULT_IN_FLIGHT
                            ) -> Iterator:
        art = _art()
        for ref in self._iter_result_refs(in_flight):
            yield art.get(ref)

    def materialize(self) -> "Dataset":
        """Execute the plan; returns an operator-free Dataset over the
        result blocks (held by refs, not driver memory)."""
        if not self._operators and not self._read_tasks:
            return self
        return Dataset(list(self._iter_result_refs()))

    # ------------------------------------------------------- consumption

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_result_blocks():
            yield from BlockAccessor.for_block(block).to_rows()

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "default",
                     drop_last: bool = False) -> Iterator:
        """Stream batches; for Arrow blocks with batch_format="numpy"
        this is the TPU ingest path (dict of numpy columns →
        jnp.asarray).  Batches assemble by block slice + concat, never
        round-tripping rows through Python, so Arrow dtypes survive."""
        from ant_ray_tpu.data.block import batches_from_blocks  # noqa: PLC0415

        yield from batches_from_blocks(self._iter_result_blocks(),
                                       batch_size, batch_format, drop_last)

    def take(self, n: int = 20) -> list:
        out: list = []
        for block in self.limit(n)._iter_result_blocks():
            out.extend(BlockAccessor.for_block(block).to_rows())
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> list:
        return [row for block in self._iter_result_blocks()
                for row in BlockAccessor.for_block(block).to_rows()]

    def count(self) -> int:
        from ant_ray_tpu.data.executor import _block_rows  # noqa: PLC0415

        art = _art()
        rows_remote = art.remote(_block_rows)
        refs = [rows_remote.remote(r) for r in self._iter_result_refs()]
        return sum(art.get(refs))

    def aggregate(self, *aggs: agg.AggregateFn) -> dict:
        """Global aggregation (single implicit group)."""
        grouped = self.groupby(lambda _row: 0)._aggregate(*aggs)
        rows = grouped.take_all()
        if not rows:
            return {a.name: a.finalize(a.init()) for a in aggs}
        row = dict(rows[0])
        row.pop("key", None)
        return row

    def schema(self):
        """Schema of the first block (Arrow) or None — only the schema
        crosses the wire; the block itself stays in the cluster."""
        art = _art()
        schema_remote = art.remote(_block_schema)
        for ref in self._iter_result_refs(in_flight=1):
            return art.get(schema_remote.remote(ref))
        return None

    # -------------------------------------------------------- reshaping

    def split(self, n: int) -> list["Dataset"]:
        """Split into n datasets block-wise (for per-worker shards)."""
        ds = self.materialize()
        shards: list[list] = [[] for _ in builtins.range(n)]
        for i, ref in enumerate(ds._block_refs):
            shards[i % n].append(ref)
        return [Dataset(refs) for refs in shards]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None, name: str = ""):
        """n coordinated streaming iterators over ONE execution of the
        plan per epoch — nothing materializes (ref: dataset.py:1881).
        ``equal=True`` gives every iterator exactly the same row count
        per epoch (what SPMD training needs).  All n iterators must be
        consumed together: each epoch starts at a barrier."""
        from ant_ray_tpu.data.iterator import make_streaming_split  # noqa: PLC0415

        del locality_hints  # single-store-per-node runtime: no-op hint
        return make_streaming_split(self, n, equal=equal, name=name)

    def iterator(self):
        """Single-consumer DataIterator over the plan (one execution
        per pass — ref: Dataset.iterator())."""
        from ant_ray_tpu.data.iterator import PlanIterator  # noqa: PLC0415

        return PlanIterator(self)

    # ---------------------------------------------------------- writers

    def write_jsonl(self, directory: str) -> list[str]:
        return self._write(directory, "jsonl", write_jsonl_block)

    def write_parquet(self, directory: str) -> list[str]:
        return self._write(directory, "parquet", write_parquet_block)

    def _write(self, directory: str, ext: str, writer) -> list[str]:
        import os  # noqa: PLC0415

        os.makedirs(directory, exist_ok=True)
        art = _art()
        write_remote = art.remote(writer)
        refs = []
        for i, ref in enumerate(self._iter_result_refs()):
            path = os.path.join(directory, f"part-{i:05d}.{ext}")
            refs.append(write_remote.remote(ref, path))
        return art.get(refs)

    # ------------------------------------------------------------- info

    @property
    def num_blocks(self) -> int:
        if self._read_tasks:
            return len(self._read_tasks) + len(self._block_refs)
        return len(self._block_refs)

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks}, "
                f"pending_operators={len(self._operators)})")


class GroupedData:
    """(ref: python/ray/data/grouped_data.py)"""

    def __init__(self, dataset: Dataset, key):
        self._dataset = dataset
        self._key = key

    def _aggregate(self, *aggs: agg.AggregateFn) -> Dataset:
        return self._dataset._with(
            L.GroupByAggregate(self._key, tuple(aggs)))

    def aggregate(self, *aggs: agg.AggregateFn) -> Dataset:
        return self._aggregate(*aggs)

    def count(self) -> Dataset:
        return self._aggregate(agg.Count())

    def sum(self, on=None) -> Dataset:
        return self._aggregate(agg.Sum(on))

    def min(self, on=None) -> Dataset:
        return self._aggregate(agg.Min(on))

    def max(self, on=None) -> Dataset:
        return self._aggregate(agg.Max(on))

    def mean(self, on=None) -> Dataset:
        return self._aggregate(agg.Mean(on))


# ------------------------------------------------------------ constructors

def from_items(items: list, parallelism: int = DEFAULT_PARALLELISM
               ) -> Dataset:
    art = _art()
    items = list(items)
    if not items:
        return Dataset([art.put([])])
    parallelism = max(1, min(parallelism, len(items)))
    size = (len(items) + parallelism - 1) // parallelism
    refs = [art.put(items[i:i + size])
            for i in builtins.range(0, len(items), size)]
    return Dataset(refs)


def range_(n: int, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(RangeDatasource(n), parallelism)


def from_numpy(array, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return from_items(list(array), parallelism)


def from_arrow(table) -> Dataset:
    art = _art()
    return Dataset([art.put(table)])


def from_pandas(df) -> Dataset:
    import pyarrow  # noqa: PLC0415

    return from_arrow(pyarrow.Table.from_pandas(df))


def read_datasource(source: Datasource,
                    parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return Dataset(read_tasks=source.get_read_tasks(parallelism))


def read_csv(paths, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(CSVDatasource(paths), parallelism)


def read_jsonl(paths, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(JSONLDatasource(paths), parallelism)


def read_parquet(paths, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(ParquetDatasource(paths), parallelism)
