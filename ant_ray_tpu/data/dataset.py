"""Dataset: lazy block-parallel transforms with streaming execution.

Architecture (scaled-down mirror of the reference, SURVEY §2.4 Data):
data is a list of *blocks* (object refs to item lists), transforms build a
lazy chain of fused per-block functions (the reference's OneToOne operator
fusion), and consumption streams blocks through tasks with a bounded
in-flight window (the StreamingExecutor's backpressure, ref:
execution/streaming_executor.py:67) so datasets larger than memory flow.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator

DEFAULT_PARALLELISM = 8
DEFAULT_IN_FLIGHT = 8


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


class Dataset:
    def __init__(self, block_refs: list, transforms: tuple = ()):
        self._block_refs = list(block_refs)
        self._transforms = tuple(transforms)

    # -------------------------------------------------------- transforms

    def _with(self, fn: Callable[[list], list]) -> "Dataset":
        return Dataset(self._block_refs, self._transforms + (fn,))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with(lambda block: [fn(x) for x in block])

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with(lambda block: [x for x in block if fn(x)])

    def flat_map(self, fn: Callable[[Any], Iterable]) -> "Dataset":
        return self._with(
            lambda block: [y for x in block for y in fn(x)])

    def map_batches(self, fn: Callable[[list], list],
                    batch_size: int | None = None) -> "Dataset":
        def apply(block: list) -> list:
            if batch_size is None:
                return list(fn(block))
            out: list = []
            for i in builtins.range(0, len(block), batch_size):
                out.extend(fn(block[i:i + batch_size]))
            return out

        return self._with(apply)

    # -------------------------------------------------------- execution

    def _fused_fn(self):
        transforms = self._transforms

        def run(block: list) -> list:
            for t in transforms:
                block = t(block)
            return block

        return run

    def materialize(self) -> "Dataset":
        """Execute all pending transforms; returns a transform-free
        Dataset over new blocks."""
        if not self._transforms:
            return self
        art = _art()
        run = self._fused_fn()
        apply_block = art.remote(lambda block: run(block))
        new_refs = [apply_block.remote(ref) for ref in self._block_refs]
        return Dataset(new_refs)

    def _iter_result_blocks(self, in_flight: int = DEFAULT_IN_FLIGHT
                            ) -> Iterator[list]:
        """Stream blocks through the transform chain with bounded
        in-flight tasks (backpressure)."""
        art = _art()
        if not self._transforms:
            for ref in self._block_refs:
                yield art.get(ref)
            return
        run = self._fused_fn()
        apply_block = art.remote(lambda block: run(block))
        pending_input = list(self._block_refs)
        running: list = []
        while pending_input or running:
            while pending_input and len(running) < in_flight:
                running.append(apply_block.remote(pending_input.pop(0)))
            ready, running = art.wait(running, num_returns=1, timeout=30.0)
            for ref in ready:
                yield art.get(ref)

    # -------------------------------------------------------- consumption

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_result_blocks():
            yield from block

    def iter_batches(self, batch_size: int = 256) -> Iterator[list]:
        buffer: list = []
        for block in self._iter_result_blocks():
            buffer.extend(block)
            while len(buffer) >= batch_size:
                yield buffer[:batch_size]
                buffer = buffer[batch_size:]
        if buffer:
            yield buffer

    def take(self, n: int = 20) -> list:
        out: list = []
        for block in self._iter_result_blocks():
            out.extend(block)
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> list:
        return [x for block in self._iter_result_blocks() for x in block]

    def count(self) -> int:
        art = _art()
        run = self._fused_fn()
        counter = art.remote(lambda block: len(run(block)))
        return sum(art.get([counter.remote(r) for r in self._block_refs]))

    # -------------------------------------------------------- reshaping

    def repartition(self, num_blocks: int) -> "Dataset":
        items = self.take_all()
        return from_items(items, parallelism=num_blocks)

    def split(self, n: int) -> list["Dataset"]:
        """Split into n datasets block-wise (for per-worker shards)."""
        ds = self.materialize()
        shards: list[list] = [[] for _ in builtins.range(n)]
        for i, ref in enumerate(ds._block_refs):
            shards[i % n].append(ref)
        return [Dataset(refs) for refs in shards]

    def random_shuffle(self, seed: int | None = None) -> "Dataset":
        import random as _random  # noqa: PLC0415

        items = self.take_all()
        _random.Random(seed).shuffle(items)
        return from_items(items, parallelism=max(1, len(self._block_refs)))

    @property
    def num_blocks(self) -> int:
        return len(self._block_refs)

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks}, "
                f"pending_transforms={len(self._transforms)})")


# ------------------------------------------------------------ constructors

def from_items(items: list, parallelism: int = DEFAULT_PARALLELISM
               ) -> Dataset:
    art = _art()
    items = list(items)
    if not items:
        return Dataset([art.put([])])
    parallelism = max(1, min(parallelism, len(items)))
    size = (len(items) + parallelism - 1) // parallelism
    refs = [art.put(items[i:i + size])
            for i in builtins.range(0, len(items), size)]
    return Dataset(refs)


def range_(n: int, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return from_items(list(builtins.range(n)), parallelism)


def from_numpy(array, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return from_items(list(array), parallelism)
