"""Datasources: lazy read tasks producing blocks (ref:
python/ray/data/datasource/ — Datasource.get_read_tasks; here each
ReadTask is a plain callable shipped to a worker, returning one block).

Tabular readers (csv / json-lines / parquet) produce Arrow blocks;
``read_numpy``/``from_items`` produce list blocks.  Writers are block
tasks too — write_jsonl / write_parquet fan out one file per block.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class ReadTask:
    """One unit of lazy input: fn() -> block."""

    fn: Callable[[], Any]


class Datasource:
    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self._n = n

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n = self._n
        parallelism = max(1, min(parallelism, n or 1))
        bounds = [round(i * n / parallelism)
                  for i in builtins.range(parallelism + 1)]

        def make(start: int, end: int) -> ReadTask:
            return ReadTask(lambda: list(builtins.range(start, end)))

        return [make(bounds[i], bounds[i + 1])
                for i in builtins.range(parallelism)]


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if not f.startswith(".")))
        elif any(c in path for c in "*?["):
            out.extend(sorted(_glob.glob(path)))
        else:
            out.append(path)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


class FileDatasource(Datasource):
    """One read task per file (the reference splits large files into
    row-group/byte-range tasks; per-file is the right granularity for
    the block sizes this engine targets)."""

    def __init__(self, paths):
        self._paths = _expand_paths(paths)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        read_file = type(self)._read_file
        return [ReadTask(lambda p=path: read_file(p))
                for path in self._paths]

    @staticmethod
    def _read_file(path: str):  # pragma: no cover — abstract
        raise NotImplementedError


class CSVDatasource(FileDatasource):
    @staticmethod
    def _read_file(path: str):
        from pyarrow import csv  # noqa: PLC0415

        return csv.read_csv(path)


class JSONLDatasource(FileDatasource):
    @staticmethod
    def _read_file(path: str):
        import json as _json  # noqa: PLC0415

        import pyarrow  # noqa: PLC0415

        with open(path) as f:
            rows = [_json.loads(line) for line in f if line.strip()]
        return pyarrow.Table.from_pylist(rows)


class ParquetDatasource(FileDatasource):
    @staticmethod
    def _read_file(path: str):
        import pyarrow.parquet as pq  # noqa: PLC0415

        return pq.read_table(path)


# --------------------------------------------------------------- writers

def write_jsonl_block(block, path: str) -> str:
    import json as _json  # noqa: PLC0415

    from ant_ray_tpu.data.block import BlockAccessor  # noqa: PLC0415

    rows = BlockAccessor.for_block(block).to_rows()
    with open(path, "w") as f:
        for row in rows:
            f.write(_json.dumps(row) + "\n")
    return path


def write_parquet_block(block, path: str) -> str:
    import pyarrow  # noqa: PLC0415
    import pyarrow.parquet as pq  # noqa: PLC0415

    if isinstance(block, list):
        block = pyarrow.Table.from_pylist(block)
    pq.write_table(block, path)
    return path
