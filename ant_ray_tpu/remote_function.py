"""@remote function machinery (ref: python/ray/remote_function.py:314)."""

from __future__ import annotations

import functools
from typing import Any, Callable

from ant_ray_tpu._private.task_options import TaskOptions


class RemoteFunction:
    """A function decorated with ``@art.remote``; call with ``.remote(...)``."""

    def __init__(self, function: Callable, options: TaskOptions | None = None):
        self._function = function
        self._options = options or TaskOptions()
        self._function_name = getattr(function, "__qualname__", repr(function))
        self._module = getattr(function, "__module__", "")
        functools.update_wrapper(self, function)

    @property
    def options_(self) -> TaskOptions:
        return self._options

    @property
    def function(self) -> Callable:
        return self._function

    @property
    def function_name(self) -> str:
        return f"{self._module}.{self._function_name}"

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function_name} cannot be called directly; "
            f"use {self._function_name}.remote(...)"
        )

    def remote(self, *args, **kwargs):
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        return global_worker.submit_task(self, args, kwargs, self._options)

    def options(self, **options) -> "RemoteFunction":
        return RemoteFunction(self._function, self._options.merged_with(**options))

    def bind(self, *args, **kwargs):
        """Build a DAG node (compiled-step-graph layer)."""
        try:
            from ant_ray_tpu.dag import FunctionNode  # noqa: PLC0415
        except ImportError as e:
            raise RuntimeError(
                "The DAG layer is not available in this build") from e
        return FunctionNode(self, args, kwargs)
