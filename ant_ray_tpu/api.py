"""Public API: init / shutdown / remote / get / put / wait / actors.

Parity surface with the reference's top-level API
(ref: python/ray/_private/worker.py:1431 ray.init, :2885 ray.get, :3032
ray.put, :3487 ray.remote).
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Sequence

from ant_ray_tpu._private import worker as worker_mod
from ant_ray_tpu._private.config import Config, global_config, set_global_config
from ant_ray_tpu._private.ids import JobID
from ant_ray_tpu._private.task_options import ActorOptions, TaskOptions
from ant_ray_tpu._private.worker import CLUSTER_MODE, LOCAL_MODE, global_worker
from ant_ray_tpu.actor import ActorClass, ActorHandle
from ant_ray_tpu.object_ref import ObjectRef
from ant_ray_tpu.remote_function import RemoteFunction


def init(
    address: str | None = None,
    *,
    local_mode: bool = False,
    num_cpus: int | None = None,
    num_tpus: int | None = None,
    resources: dict | None = None,
    object_store_memory: int | None = None,
    namespace: str | None = None,
    _system_config: dict | None = None,
    ignore_reinit_error: bool = False,
) -> "ClientContext":
    """Start (or connect to) a cluster and bind this process as a driver.

    - ``address=None``: start a fresh single-node cluster in subprocesses
      (head control store + node daemon + workers), like ``ray.init()``.
    - ``address="host:port"``: connect to an existing head as a driver
      (the process must be on a cluster node).
    - ``address="art://host:port"``: connect to a client proxy server
      from OUTSIDE the cluster (ref: ray.init("ray://...") — Ray Client);
      no daemons run locally, every call is proxied.
    - ``local_mode=True``: synchronous in-process execution, no daemons.
    """
    if global_worker.connected:
        if ignore_reinit_error:
            return ClientContext(global_worker.mode or "")
        raise RuntimeError("ant_ray_tpu.init() called twice; "
                           "pass ignore_reinit_error=True to allow")

    config = Config().apply_env_overrides().apply_dict(_system_config)
    # Propagate _system_config to the daemons/workers this driver will
    # spawn: flags travel as ART_<NAME> env vars, the same channel the
    # reference uses to embed _system_config into raylet launch
    # (ref: services.py:1518).
    if _system_config:
        import json as _json  # noqa: PLC0415

        for key, value in _system_config.items():
            name = f"ART_{key.upper()}"
            _exported_config_env.append((name, os.environ.get(name)))
            os.environ[name] = (
                _json.dumps(value) if isinstance(value, (dict, list))
                else str(value))
    if object_store_memory:
        config.object_store_memory = object_store_memory
    set_global_config(config)
    # The worker singleton's import-time factory calls cached a
    # pre-init lockcheck verdict; re-evaluate now that _system_config
    # is applied (daemons get theirs via the env export above).
    from ant_ray_tpu._lint import lockcheck  # noqa: PLC0415

    lockcheck.refresh_enabled()

    job_id = JobID.from_random()
    global_worker.job_id = job_id

    if local_mode:
        global_worker.runtime = worker_mod.LocalModeRuntime(job_id)
        global_worker.mode = LOCAL_MODE
        return ClientContext(LOCAL_MODE)

    if address is not None and address.startswith("art://"):
        from ant_ray_tpu.util.client import ClientRuntime  # noqa: PLC0415

        global_worker.runtime = ClientRuntime.connect(
            address.removeprefix("art://"))
        global_worker.mode = CLUSTER_MODE
        _register_atexit_once()
        return ClientContext(CLUSTER_MODE)

    try:
        from ant_ray_tpu._private.core import ClusterRuntime  # noqa: PLC0415
    except ImportError as e:
        raise RuntimeError(
            "Cluster mode is not available in this build; "
            "use init(local_mode=True)"
        ) from e

    global_worker.runtime = ClusterRuntime.create(
        address=address,
        job_id=job_id,
        num_cpus=num_cpus,
        num_tpus=num_tpus,
        resources=resources,
        namespace=namespace or "default",
        config=config,
    )
    global_worker.mode = CLUSTER_MODE
    # Continuous CPU profiling of the driver itself (submission-path
    # attribution: serialize vs frame-encode vs task_events vs io-loop
    # — the item the `profile --diff` A/B tool exists for).
    from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

    cpu_profiler.start("driver")
    _register_atexit_once()
    return ClientContext(
        CLUSTER_MODE,
        dashboard_url=getattr(global_worker.runtime, "dashboard_url", ""))


_atexit_registered = False


def _register_atexit_once():
    global _atexit_registered
    if not _atexit_registered:
        import atexit  # noqa: PLC0415

        atexit.register(shutdown)  # shutdown() is idempotent
        _atexit_registered = True


class ClientContext:
    def __init__(self, mode: str, dashboard_url: str = ""):
        self.mode = mode
        self.dashboard_url = dashboard_url

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        shutdown()

    def disconnect(self):
        shutdown()


_exported_config_env: list = []


def shutdown() -> None:
    from ant_ray_tpu._private import task_events  # noqa: PLC0415
    from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

    try:
        task_events.flush()  # drain before the runtime goes away
    except Exception:  # noqa: BLE001 — observability must not block
        pass             # the disconnect (events are best-effort)
    cpu_profiler.stop()  # idempotent; final publish rides the runtime
    global_worker.shutdown()
    # Undo _system_config env exports (restoring any pre-existing user
    # value) so the next init() in this process starts clean.
    while _exported_config_env:
        name, prior = _exported_config_env.pop()
        if prior is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prior


def is_initialized() -> bool:
    return global_worker.connected


def remote(*args, **options):
    """``@remote`` decorator for functions and classes
    (ref: worker.py:3487)."""
    if len(args) == 1 and not options and (
            inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote with arguments must be used as "
                        "@remote(num_cpus=..., ...)")

    def decorator(fn_or_cls):
        return _make_remote(fn_or_cls, options)

    return decorator


def _make_remote(fn_or_cls, options: dict):
    if inspect.isclass(fn_or_cls):
        opts = ActorOptions().merged_with(**options)
        return ActorClass(fn_or_cls, opts)
    opts = TaskOptions().merged_with(**options)
    return RemoteFunction(fn_or_cls, opts)


def method(num_returns: int = 1, concurrency_group: str = ""):
    """Per-method options on actor classes (ref: ray.method)."""

    def decorator(fn):
        fn.__art_num_returns__ = num_returns
        if concurrency_group:
            fn.__art_concurrency_group__ = concurrency_group
        return fn

    return decorator


def get(refs, *, timeout: float | None = None):
    return global_worker.get(refs, timeout)


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed")
    global_worker._check_connected()
    return global_worker.put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: float | None = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return global_worker.wait(refs, num_returns, timeout, fetch_local)


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    global_worker._check_connected()
    return global_worker.runtime.get_actor(name, namespace)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    global_worker._check_connected()
    global_worker.runtime.kill_actor(actor, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    global_worker._check_connected()
    global_worker.runtime.cancel(ref, force, recursive)


def cluster_resources() -> dict:
    global_worker._check_connected()
    return global_worker.runtime.cluster_resources()


def available_resources() -> dict:
    global_worker._check_connected()
    return global_worker.runtime.available_resources()


def nodes() -> list[dict]:
    global_worker._check_connected()
    return global_worker.runtime.nodes()


def timeline(filename: str | None = None):
    """Chrome-trace dump of the cluster's task schedule (ref:
    ray.timeline)."""
    global_worker._check_connected()
    from ant_ray_tpu.util.timeline import timeline as _timeline  # noqa: PLC0415

    return _timeline(filename)
