"""``python -m ant_ray_tpu`` — the operator CLI (see cli.py)."""

from ant_ray_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
