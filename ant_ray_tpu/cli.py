"""Operator CLI: ``python -m ant_ray_tpu <subcommand>`` (ref: the
`ray list/summary/memory/status` CLI over ray.util.state).

Talks STRAIGHT to the cluster's RPC surfaces through a ClientPool — no
worker runtime, no driver registration: the CLI is a read-only
operator tool that must work against a wedged cluster that can't take
new drivers.  Every subcommand renders a human table by default and
the raw reply with ``--json`` (one JSON document on stdout — pipe to
jq).

    art() { python -m ant_ray_tpu "$@"; }
    art status
    art list tasks --state RUNNING --limit 20
    art summary tasks
    art memory --top 10
    art list objects | nodes | actors | placement-groups | jobs
    art logs            # per-node log files;  art logs <file> --tail 100
    art trace <trace_id>

The cluster address comes from ``--address`` or the ``ART_ADDRESS``
environment variable (the same one job drivers use).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


# ------------------------------------------------------------ rendering

def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if value < 1024 or unit == "TiB":
            return (f"{value:.0f}{unit}" if unit == "B"
                    else f"{value:.1f}{unit}")
        value /= 1024
    return str(n)


def _fmt(value) -> str:
    if value is None:
        return "-"
    if isinstance(value, bool):
        return "yes" if value else "no"
    if isinstance(value, float):
        return f"{value:.3f}"
    if isinstance(value, (list, tuple)):
        return ",".join(str(v) for v in value) or "-"
    if isinstance(value, dict):
        return json.dumps(value, sort_keys=True)
    return str(value)


def _table(rows: list[dict], columns: list[tuple[str, str]],
           out=sys.stdout) -> None:
    """Plain aligned columns: (key, HEADER) pairs; missing keys render
    as '-'.  No box-drawing — output must survive grep/awk."""
    headers = [header for _key, header in columns]
    cells = [[_fmt(row.get(key)) for key, _header in columns]
             for row in rows]
    widths = [max(len(headers[i]), *(len(r[i]) for r in cells))
              if cells else len(headers[i]) for i in range(len(columns))]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)),
          file=out)
    for row in cells:
        print("  ".join(c.ljust(w) for c, w in zip(row, widths)),
              file=out)
    if not rows:
        print("(none)", file=out)


def _short(value, n: int = 16):
    return value[:n] if isinstance(value, str) else value


# ------------------------------------------------------------ transport

class StateClient:
    """Thin RPC facade over the head + per-node daemons."""

    def __init__(self, address: str):
        from ant_ray_tpu._private.protocol import ClientPool  # noqa: PLC0415

        self.address = address
        self.pool = ClientPool()
        self.gcs = self.pool.get(address)

    def call(self, method: str, payload: dict | None = None,
             timeout: float = 30.0):
        return self.gcs.call(method, payload or {}, timeout=timeout)

    def alive_nodes(self) -> dict[str, str]:
        from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
            _alive_nodes,
        )

        return _alive_nodes(self.gcs)


def _resolve_address(args) -> str:
    address = args.address or os.environ.get("ART_ADDRESS")
    if not address:
        print("error: no cluster address — pass --address host:port or "
              "set ART_ADDRESS", file=sys.stderr)
        raise SystemExit(2)
    return address


def _emit(args, payload, render) -> None:
    """--json prints the raw reply; otherwise the human renderer runs."""
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        render(payload)


# ------------------------------------------------------------ commands

def cmd_status(client: StateClient, args) -> int:
    nodes = client.call("GetAllNodes")
    actors = client.call("ListActors")
    total = client.call("ClusterResources")
    avail = client.call("AvailableResources")
    try:
        tasks = client.call("SummarizeTasks")
    except Exception:  # noqa: BLE001 — pre-observatory head
        tasks = None
    try:
        ha = client.call("GetHaView")
    except Exception:  # noqa: BLE001 — pre-HA head
        ha = None
    stores = []
    for node_id, address in client.alive_nodes().items():
        try:
            store = client.pool.get(address).call("GetStoreStats", {},
                                                  timeout=5)
        except Exception:  # noqa: BLE001 — node mid-death
            continue
        stores.append({"node_id": node_id, **store})
    actor_states: dict[str, int] = {}
    for actor in actors:
        actor_states[actor["state"]] = \
            actor_states.get(actor["state"], 0) + 1
    payload = {
        "address": client.address,
        "ha": ha,
        "nodes": {"alive": sum(i.alive for i in nodes.values()),
                  "dead": sum(not i.alive for i in nodes.values()),
                  "draining": sum(
                      bool(getattr(i, "draining", False))
                      for i in nodes.values() if i.alive)},
        "resources_total": total,
        "resources_available": avail,
        "actors": actor_states,
        "tasks": (None if tasks is None else {
            "total": tasks["total_tasks"],
            "dropped": tasks["num_tasks_dropped"],
            "states": _merge_state_counts(tasks)}),
        "object_store": {
            "used": sum(s["used"] for s in stores),
            "capacity": sum(s["capacity"] for s in stores),
            "spilled": sum(s["spilled"] for s in stores)},
    }

    def render(p):
        n = p["nodes"]
        print(f"cluster   {p['address']}")
        view = p.get("ha")
        if view and view.get("ha"):
            print(f"control   leader {view.get('leader') or '?'} "
                  f"(term {view.get('term')})")
            standbys = [r for r in view.get("replicas", ())
                        if r.get("role") != "leader"]
            for r in standbys:
                lag = r.get("lag_s")
                print(f"  standby {r.get('address')} "
                      f"[{r.get('replica_id')}]"
                      + (f" lag {lag * 1000:.0f}ms"
                         if lag is not None else ""))
            failover = view.get("last_failover_ts")
            if failover:
                import datetime  # noqa: PLC0415

                stamp = datetime.datetime.fromtimestamp(failover)
                print(f"  last failover {stamp:%Y-%m-%d %H:%M:%S}")
        print(f"nodes     {n['alive']} alive / {n['dead']} dead"
              + (f" / {n['draining']} draining" if n["draining"]
                 else ""))
        for res, tot in sorted(p["resources_total"].items()):
            free = p["resources_available"].get(res, 0.0)
            print(f"  {res:<12} {tot - free:g}/{tot:g} used")
        if p["actors"]:
            print("actors    " + ", ".join(
                f"{k}={v}" for k, v in sorted(p["actors"].items())))
        if p["tasks"]:
            states = ", ".join(f"{k}={v}" for k, v in
                               sorted(p["tasks"]["states"].items()))
            print(f"tasks     {p['tasks']['total']} tracked"
                  + (f" ({states})" if states else "")
                  + (f", {p['tasks']['dropped']} dropped by GC"
                     if p["tasks"]["dropped"] else ""))
        store = p["object_store"]
        print(f"objects   {_fmt_bytes(store['used'])} / "
              f"{_fmt_bytes(store['capacity'])} in store"
              + (f", {_fmt_bytes(store['spilled'])} spilled"
                 if store["spilled"] else ""))

    _emit(args, payload, render)
    return 0


def _merge_state_counts(summary: dict) -> dict:
    out: dict[str, int] = {}
    for group in summary["summary"].values():
        for state, count in group["state_counts"].items():
            out[state] = out.get(state, 0) + count
    return out


def cmd_list(client: StateClient, args) -> int:
    kind = args.kind
    if kind == "tasks":
        reply = client.call("ListTasks", {
            "state": args.state, "name": args.name, "job_id": args.job,
            "actor_id": args.actor, "node_id": args.node,
            "limit": args.limit,
            "token": int(args.token) if args.token is not None
            else None})

        def render(p):
            for t in p["tasks"]:
                t["task"] = _short(t["task_id"])
                t["node"] = _short(t["node_id"] or "", 12)
            _table(p["tasks"], [("task", "TASK"), ("attempt", "ATT"),
                                ("name", "NAME"), ("state", "STATE"),
                                ("node", "NODE"), ("queue_s", "QUEUE_S"),
                                ("run_s", "RUN_S"), ("error", "ERROR")])
            if p.get("next_token") is not None:
                print(f"... more — continue with --token "
                      f"{p['next_token']}")
            if p.get("num_tasks_dropped"):
                print(f"({p['num_tasks_dropped']} records dropped by "
                      "table GC)")

        _emit(args, reply, render)
        return 0
    if kind == "actors":
        actors = client.call("ListActors")
        if args.state:
            actors = [a for a in actors if a["state"] == args.state]
        actors = actors[:args.limit]

        def render(rows):
            for a in rows:
                a["actor"] = _short(a["actor_id"])
                a["node"] = _short(a.get("node_id") or "", 12)
            _table(rows, [("actor", "ACTOR"), ("class_name", "CLASS"),
                          ("name", "NAME"), ("state", "STATE"),
                          ("node", "NODE"),
                          ("death_reason", "DEATH_REASON")])

        _emit(args, actors, render)
        return 0
    if kind == "objects":
        from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
            list_objects_joined,
        )

        objects = list_objects_joined(client.gcs, client.pool)
        if args.node:
            objects = [o for o in objects
                       if any(loc.startswith(args.node)
                              for loc in o["locations"])]
        objects.sort(key=lambda o: o["size"] or 0, reverse=True)
        objects = objects[:args.limit]

        def render(rows):
            for o in rows:
                o["object"] = _short(o["object_id"])
                o["bytes"] = _fmt_bytes(o["size"])
                o["nodes"] = [loc[:8] for loc in o["locations"]]
                o["tier"] = sorted({c["tier"] for c in o["copies"]
                                    if c.get("tier")}) or None
            _table(rows, [("object", "OBJECT"), ("bytes", "SIZE"),
                          ("nodes", "NODES"), ("tier", "TIER"),
                          ("pinned", "PINNED"), ("owner", "OWNER"),
                          ("callsite", "CALLSITE")])

        _emit(args, objects, render)
        return 0
    if kind == "nodes":
        # Server-side page + state filter (the ListTasks cursor idiom):
        # a 1000-node listing no longer ships the whole node table per
        # call, and `--state DEAD` filters at the source.
        reply = client.call("ListNodes", {
            "limit": args.limit, "token": args.token,
            "state": args.state})

        def render(p):
            for n in p["nodes"]:
                n["node"] = n["node_id"][:12]
                n["resources"] = n["total_resources"]
            _table(p["nodes"],
                   [("node", "NODE"), ("address", "ADDRESS"),
                    ("state", "STATE"), ("resources", "RESOURCES"),
                    ("labels", "LABELS")])
            if p.get("next_token"):
                print(f"... more — continue with --token "
                      f"{p['next_token']}")
            print(f"({len(p['nodes'])} shown, {p['matched']} matched, "
                  f"{p['total']} total)")

        _emit(args, reply, render)
        return 0
    if kind == "placement-groups":
        pgs = client.call("ListPlacementGroups")
        rows = [{"pg_id": pg_id, "pg": pg_id[:16], **record}
                for pg_id, record in pgs.items()]

        def render(r):
            _table(r, [("pg", "GROUP"), ("name", "NAME"),
                       ("state", "STATE"), ("strategy", "STRATEGY"),
                       ("bundles", "BUNDLES")])

        _emit(args, rows, render)
        return 0
    if kind == "jobs":
        jobs = client.call("ListJobs")

        def render(r):
            _table(r, [("job_id", "JOB"),
                       ("driver_address", "DRIVER"),
                       ("started_at", "STARTED_AT")])

        _emit(args, jobs, render)
        return 0
    print(f"error: unknown list kind {kind!r}", file=sys.stderr)
    return 2


def cmd_scale_report(args) -> int:
    """Control-plane cost curves: the committed sweep
    (BENCH_scale.json, written by benchmarks/scale_harness.py) plus —
    when a cluster is reachable — the live GetScaleStats attribution
    snapshot from the head."""
    report = None
    if args.file and os.path.exists(args.file):
        with open(args.file) as f:
            report = json.load(f)
    live = None
    address = args.address or os.environ.get("ART_ADDRESS")
    if address:
        try:
            client = StateClient(address)
            try:
                live = client.call("GetScaleStats", timeout=10)
            finally:
                client.pool.close_all()
        except Exception as e:  # noqa: BLE001 — report works offline
            print(f"(no live cluster at {address}: {e})",
                  file=sys.stderr)
    if report is None and live is None:
        print(f"error: no sweep file at {args.file!r} and no "
              "reachable cluster — run benchmarks/scale_harness.py "
              "or pass --address", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"sweep": report, "live": live}, indent=2,
                         default=str))
        return 0
    if report is not None:
        print("== scale sweep "
              f"({report.get('generated_at', 'uncommitted')}, "
              f"{report['config'].get('cpu_count')} cpu) ==")
        rows = [{
            "nodes": r["nodes"],
            "leases_s": r.get("leases_per_s"),
            "hb_cpu_ms_100n": r.get("heartbeat_cpu_ms_per_s_per_100n"),
            "duty": r.get("gcs_io_loop_duty_loaded"),
            "scan_w": r.get("sched_scanned_nodes_per_pick"),
            "hit": r.get("pick_cache_hit_rate"),
            "failover_s": r.get("failover_s"),
        } for r in report.get("sweep", [])]
        _table(rows, [("nodes", "NODES"), ("leases_s", "LEASES/S"),
                      ("hb_cpu_ms_100n", "HB_CPU_MS/S/100N"),
                      ("duty", "IO_DUTY"), ("scan_w", "SCAN_WIDTH"),
                      ("hit", "CACHE_HIT"),
                      ("failover_s", "FAILOVER_S")])
        fix = report.get("cliff_fix") or {}
        if fix.get("nocache_sweep"):
            print(f"\n-- cliff fix: {fix.get('name')} "
                  f"({fix.get('flag')}=0 arm) --")
            _table([{"nodes": r["nodes"],
                     "leases_s": r.get("leases_per_s"),
                     "scan_w": r.get("sched_scanned_nodes_per_pick")}
                    for r in fix["nocache_sweep"]],
                   [("nodes", "NODES"), ("leases_s", "LEASES/S"),
                    ("scan_w", "SCAN_WIDTH")])
    if live is not None:
        print("\n== live head ==")
        print(f"table rows  {live['table_rows']}")
        print(f"rings       {live['rings']}")
        print(f"subscribers {live['subscribers']}   "
              f"io-loop duty {live.get('io_loop_duty')}")
        print(f"scheduler   {live['sched']}")
        print(f"heartbeat   {live['heartbeat']}")
        handle = sorted(live.get("handle", {}).items(),
                        key=lambda kv: -kv[1][1])[:args.top]
        rows = [{"method": m, "calls": c,
                 "total_ms": round(ns / 1e6, 2),
                 "us_per_call": round(ns / c / 1e3, 2) if c else None}
                for m, (c, ns) in handle]
        print(f"\n-- top {len(rows)} methods by server handle time --")
        _table(rows, [("method", "METHOD"), ("calls", "CALLS"),
                      ("total_ms", "TOTAL_MS"),
                      ("us_per_call", "US/CALL")])
    return 0


def cmd_summary(client: StateClient, args) -> int:
    reply = client.call("SummarizeTasks", {"job_id": args.job})

    def render(p):
        rows = []
        for name, group in sorted(p["summary"].items()):
            run = group.get("run_s") or {}
            rows.append({
                "name": name, "total": group["total"],
                "states": ", ".join(
                    f"{k}={v}" for k, v in
                    sorted(group["state_counts"].items())),
                "mean_s": run.get("mean"), "p50_s": run.get("p50"),
                "p99_s": run.get("p99")})
        _table(rows, [("name", "NAME"), ("total", "TOTAL"),
                      ("states", "STATES"), ("mean_s", "MEAN_S"),
                      ("p50_s", "P50_S"), ("p99_s", "P99_S")])
        if p.get("num_tasks_dropped"):
            print(f"({p['num_tasks_dropped']} records dropped by table "
                  "GC)")
        if p.get("task_events_dropped"):
            print(f"({p['task_events_dropped']} events dropped by "
                  "producer buffers)")

    _emit(args, reply, render)
    return 0


def cmd_memory(client: StateClient, args) -> int:
    from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
        build_memory_report,
    )

    report = build_memory_report(client.gcs, client.pool,
                                 top_n=args.top)

    def render(p):
        print("per-node object store:")
        node_rows = [dict(n, node=n["node_id"][:12],
                          used_h=_fmt_bytes(n["used"]),
                          cap_h=_fmt_bytes(n["capacity"]),
                          spill_h=_fmt_bytes(n["spilled"]))
                     for n in p["nodes"]]
        _table(node_rows, [("node", "NODE"), ("used_h", "USED"),
                           ("cap_h", "CAPACITY"),
                           ("spill_h", "SPILLED"),
                           ("objects", "OBJECTS")])
        print(f"\ntop {len(p['objects'])} objects by size:")
        obj_rows = []
        for o in p["objects"]:
            refs = o.get("refs")
            obj_rows.append({
                "object": _short(o["object_id"]),
                "bytes": _fmt_bytes(o["size"]),
                "holders": [loc[:8] for loc in o["locations"]],
                "pinned": o["pinned"],
                "owner": o.get("owner"),
                "refs": ("-" if refs is None else
                         f"local={refs['local_refs']} "
                         f"borrows={refs['borrows']} "
                         f"pins={refs['pins']}"),
                "leak": o.get("leak"),
                "callsite": o.get("callsite")})
        _table(obj_rows, [("object", "OBJECT"), ("bytes", "SIZE"),
                          ("holders", "HOLDERS"), ("pinned", "PINNED"),
                          ("owner", "OWNER"), ("refs", "REFS"),
                          ("leak", "LEAK"), ("callsite", "CALLSITE")])
        t = p["totals"]
        print(f"\ntotal {t['objects']} objects, "
              f"{_fmt_bytes(t['bytes'])} "
              f"({t['pinned_objects']} pinned, "
              f"{_fmt_bytes(t['chunk_cache_bytes'])} chunk cache)")
        if p["leak_candidates"]:
            print(f"leak candidates: {len(p['leak_candidates'])} "
                  "(see LEAK column: owner_dead = owning worker "
                  "unreachable; no_live_reference = owner holds no "
                  "reference)")

    _emit(args, report, render)
    return 0


def cmd_logs(client: StateClient, args) -> int:
    nodes = client.alive_nodes()
    if args.node:
        nodes = {nid: addr for nid, addr in nodes.items()
                 if nid.startswith(args.node)}
        if not nodes:
            print(f"error: no alive node matches {args.node!r}",
                  file=sys.stderr)
            return 1
    if not args.filename:
        listing = []
        for node_id, address in sorted(nodes.items()):
            try:
                files = client.pool.get(address).call("ListLogs", {},
                                                      timeout=5)
            except Exception:  # noqa: BLE001 — node mid-death
                continue
            listing.append({"node_id": node_id, "files": files})

        def render(rows):
            for entry in rows:
                print(f"node {entry['node_id'][:12]}:")
                for f in entry["files"]:
                    print(f"  {_fmt_bytes(f['size']):>10}  "
                          f"{f['filename']}")

        _emit(args, listing, render)
        return 0
    last_error = "no nodes"
    for node_id, address in sorted(nodes.items()):
        try:
            reply = client.pool.get(address).call("ReadLog", {
                "filename": args.filename, "tail": args.tail,
                "max_bytes": args.max_bytes}, timeout=10)
        except Exception as e:  # noqa: BLE001 — node mid-death: try next
            last_error = f"{node_id[:12]}: {e}"
            continue
        if "error" in reply:
            last_error = reply["error"]
            continue
        text = reply["data"].decode("utf-8", errors="replace")
        if args.json:
            print(json.dumps({"node_id": node_id, "data": text,
                              "eof": reply.get("eof")}))
        else:
            sys.stdout.write(text)
        return 0
    print(f"error: {last_error}", file=sys.stderr)
    return 1


def cmd_profile(client: StateClient, args) -> int:
    """Whole-cluster CPU capture: wait out the window, then merge every
    process's published folded-stack deltas into ONE collapsed-stack
    document (flamegraph.pl / speedscope input).  ``--out`` writes the
    capture JSON that ``--diff`` consumes."""
    import time  # noqa: PLC0415

    from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

    t0 = time.time()
    duration = max(float(args.duration), 0.0)
    if duration:
        time.sleep(duration)
    # Samplers publish on a period, not at capture edges: poll a short
    # grace window until the record set stops growing, so a capture
    # barely longer than one publish period still lands every process.
    records: list = []
    deadline = time.monotonic() + 6.0
    while True:
        payload: dict = {"since_ts": t0}
        if args.node:
            payload["node_id"] = args.node
        fresh = client.call("CpuProfileGet", payload) or []
        if len(fresh) > len(records):
            records = fresh
        elif records:
            break
        if time.monotonic() > deadline:
            break
        time.sleep(0.5)
    merged = cpu_profiler.merge_folded(records)
    capture = {
        "ts": t0, "duration_s": duration,
        "node_filter": args.node,
        "records": len(records),
        "procs": sorted({r.get("proc", "?") for r in records}),
        "samples": sum(int(r.get("samples") or 0) for r in records),
        "stacks": merged,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(capture, f)

    def render(p):
        print(cpu_profiler.render_folded(p["stacks"]))
        print(f"# {p['records']} records, {p['samples']} samples, "
              f"procs: {','.join(p['procs']) or '-'}",
              file=sys.stderr)

    _emit(args, capture, render)
    return 0


def cmd_profile_diff(args) -> int:
    """A/B two capture JSONs (from ``profile --out``): frames ranked by
    self-time delta, B minus A — regressions first."""
    from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

    with open(args.diff[0]) as f:
        a = json.load(f)
    with open(args.diff[1]) as f:
        b = json.load(f)
    rows = cpu_profiler.diff_folded(a.get("stacks") or {},
                                    b.get("stacks") or {})
    payload = {"a": args.diff[0], "b": args.diff[1],
               "frames": [{"frame": frame, "delta": delta,
                           "a": sa, "b": sb}
                          for frame, delta, sa, sb in rows]}

    def render(p):
        _table(p["frames"], [("frame", "FRAME"), ("delta", "DELTA"),
                             ("a", "A_SAMPLES"), ("b", "B_SAMPLES")])

    _emit(args, payload, render)
    return 0


def cmd_trace(client: StateClient, args) -> int:
    from ant_ray_tpu.observability.tracing_plane import span_tree  # noqa: PLC0415

    spans = client.call("SpanEventsGet",
                        {"trace_id": args.trace_id}) or []
    payload = {"trace_id": args.trace_id, "span_count": len(spans),
               "tree": span_tree(spans)}

    def render(p):
        if not p["span_count"]:
            print(f"no spans for trace {args.trace_id} (sampled? "
                  "published yet?)")
            return

        def walk(node, depth):
            dur = node.get("dur_s")
            dur_text = f"{dur * 1000:.1f}ms" if dur is not None else "-"
            flags = " ERROR" if node.get("error") else ""
            print(f"{'  ' * depth}{node['name']}  {dur_text}  "
                  f"[{node.get('node_id', '')}:{node.get('pid', '')}]"
                  f"{flags}")
            for child in node.get("children", ()):
                walk(child, depth + 1)

        for root in p["tree"]:
            walk(root, 0)

    _emit(args, payload, render)
    return 0


# ------------------------------------------------------------- argparse

def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m ant_ray_tpu",
        description="cluster state observatory CLI")
    parser.add_argument("--address", default=None,
                        help="cluster head host:port (default: "
                             "$ART_ADDRESS)")
    parser.add_argument("--json", action="store_true",
                        help="print the raw reply as JSON")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("status", help="one-screen cluster overview")

    p_list = sub.add_parser("list", help="list cluster entities")
    p_list.add_argument("kind", choices=[
        "tasks", "actors", "objects", "nodes", "placement-groups",
        "jobs"])
    p_list.add_argument("--state", default=None,
                        help="filter by state (tasks/actors/nodes; "
                             "nodes: ALIVE|DEAD|DRAINING)")
    p_list.add_argument("--name", default=None,
                        help="filter tasks by function name")
    p_list.add_argument("--job", default=None,
                        help="filter tasks by job id (hex)")
    p_list.add_argument("--actor", default=None,
                        help="filter tasks by actor id (hex)")
    p_list.add_argument("--node", default=None,
                        help="filter by node id prefix")
    p_list.add_argument("--limit", type=int, default=100)
    p_list.add_argument("--token", default=None,
                        help="continuation token from the previous "
                             "page (tasks: int; nodes: node-id hex)")

    p_summary = sub.add_parser("summary", help="server-side rollups")
    p_summary.add_argument("kind", choices=["tasks"])
    p_summary.add_argument("--job", default=None)

    p_memory = sub.add_parser(
        "memory", help="object memory attribution (`ray memory` "
                       "analog)")
    p_memory.add_argument("--top", type=int, default=20,
                          help="how many objects by size")

    p_logs = sub.add_parser("logs", help="list / read node log files")
    p_logs.add_argument("filename", nargs="?", default=None)
    p_logs.add_argument("--node", default=None,
                        help="node id prefix")
    p_logs.add_argument("--tail", type=int, default=None)
    p_logs.add_argument("--max-bytes", type=int, default=65536)

    p_trace = sub.add_parser("trace",
                             help="render one request's span tree")
    p_trace.add_argument("trace_id")

    p_profile = sub.add_parser(
        "profile", help="whole-cluster collapsed-stack CPU capture "
                        "(flamegraph.pl / speedscope input)")
    p_profile.add_argument("--node", default=None,
                           help="node id prefix (default: every node)")
    p_profile.add_argument("--all", action="store_true",
                           help="whole cluster (the default; explicit "
                                "for scripts)")
    p_profile.add_argument("--duration", type=float, default=5.0,
                           help="capture window seconds")
    p_profile.add_argument("--out", default=None,
                           help="write the capture JSON here (the "
                                "--diff input format)")
    p_profile.add_argument("--diff", nargs=2,
                           metavar=("A.json", "B.json"), default=None,
                           help="rank frames by self-time delta "
                                "between two captures (no cluster "
                                "needed)")

    p_scale = sub.add_parser(
        "scale-report", help="control-plane cost curves (committed "
                             "BENCH_scale.json + live GetScaleStats)")
    p_scale.add_argument("--file", default="BENCH_scale.json",
                         help="sweep JSON from "
                              "benchmarks/scale_harness.py")
    p_scale.add_argument("--top", type=int, default=12,
                         help="methods shown in the handle-time "
                              "ranking")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "profile" and args.diff:
        return cmd_profile_diff(args)  # purely local — no cluster
    if args.command == "scale-report":
        return cmd_scale_report(args)  # works offline from the file
    client = StateClient(_resolve_address(args))
    try:
        if args.command == "status":
            return cmd_status(client, args)
        if args.command == "list":
            return cmd_list(client, args)
        if args.command == "summary":
            return cmd_summary(client, args)
        if args.command == "memory":
            return cmd_memory(client, args)
        if args.command == "logs":
            return cmd_logs(client, args)
        if args.command == "trace":
            return cmd_trace(client, args)
        if args.command == "profile":
            return cmd_profile(client, args)
        return 2
    finally:
        client.pool.close_all()


if __name__ == "__main__":
    raise SystemExit(main())
