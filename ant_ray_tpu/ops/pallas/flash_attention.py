"""Pallas TPU flash-attention forward kernel.

Blocked online-softmax attention: grid (batch, heads, q_blocks, kv_blocks)
with the KV dimension innermost, accumulators living in VMEM scratch across
the KV sweep.  Q·Kᵀ and P·V land on the MXU in fp32 accumulation; the
backward pass recomputes via the blockwise-JAX path (see ops/attention.py),
so this kernel stays residual-free.

GQA is handled in the BlockSpec index maps (KV head = q head // groups) —
no materialized head repeat.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
            scale: float, causal: bool, block_q: int, block_k: int,
            num_kv_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: KV blocks strictly above the diagonal contribute nothing.
    q_start = iq * block_q
    k_start = ik * block_k
    needed = (not causal) or (k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _attend():
        # Keep matmul inputs in the native (bf16) dtype — the MXU runs at
        # full rate with fp32 accumulation via preferred_element_type.
        q = q_ref[0, 0]                                      # (BQ, D)
        k = k_ref[0, 0]                                      # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (BQ, BK)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos > q_pos, NEG_INF, s)

        m_prev = m_ref[:]                                    # (BQ, 128)
        s_max = jnp.max(s, axis=-1, keepdims=True)           # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(s_max, m_prev.shape))
        p = jnp.exp(s - m_new[:, :1])                        # (BQ, BK)
        corr = jnp.exp(m_prev - m_new)                       # (BQ, 128)
        l_ref[:] = l_ref[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), corr.shape)
        v = v_ref[0, 0]                                      # (BK, D)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (BQ, D)
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv
        m_ref[:] = m_new

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"))
def flash_attention_forward(q, k, v, *, causal: bool = True,
                            scale: float | None = None,
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool | None = None):
    """q: (batch, q_len, heads, dim); k/v: (batch, kv_len, kv_heads, dim).
    Returns (batch, q_len, heads, dim) in q.dtype."""
    batch, q_len, num_heads, head_dim = q.shape
    kv_len, num_kv_heads = k.shape[1], k.shape[2]
    groups = num_heads // num_kv_heads
    scale_val = scale if scale is not None else head_dim ** -0.5
    if q_len % block_q or kv_len % block_k:
        raise ValueError(
            f"sequence lengths ({q_len}, {kv_len}) must tile by "
            f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    qt = q.transpose(0, 2, 1, 3)                             # (B,H,S,D)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    num_q_blocks = q_len // block_q
    num_kv_blocks = kv_len // block_k
    grid = (batch, num_heads, num_q_blocks, num_kv_blocks)

    kernel = functools.partial(
        _kernel, scale=scale_val, causal=causal, block_q=block_q,
        block_k=block_k, num_kv_blocks=num_kv_blocks)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, head_dim),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
