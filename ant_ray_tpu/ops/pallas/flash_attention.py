"""Pallas TPU flash-attention kernels: forward (with logsumexp
residuals) and backward (dq and dk/dv sweeps).

Forward: grid (batch, heads, q_blocks, kv_blocks) with the KV dimension
innermost, online-softmax accumulators in VMEM scratch across the KV
sweep.  Q·Kᵀ and P·V land on the MXU in fp32 accumulation.  Emits the
per-row logsumexp so the backward never re-derives softmax statistics.

Backward: the standard two-sweep flash backward —
* dq kernel: grid (b, h, q_blocks, kv_blocks), dq accumulated across the
  KV sweep; recomputes p from (q, k, lse), needs delta = rowsum(dO·O)
  (computed in plain JAX — one cheap fused elementwise reduce).
* dkv kernel: grid (b, kv_heads, kv_blocks, q_blocks · groups) — each KV
  head accumulates dk/dv across all its query heads and q blocks in one
  scratch sweep, so GQA needs no materialized head repeat and no
  cross-program reduction.

Block sizes default to (256, 1024) for the forward and (256, 512) for
the backward — measured ~2.5× faster than 128×128 tiles on v5e (bigger
tiles amortize the per-program softmax/VPU work against MXU time).
Causal skipping happens at block granularity in every kernel.

GQA is handled in the BlockSpec index maps (KV head = q head // groups).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# v5e-measured (llama-400m train step, batch 8 x seq 2048, r5 sweep):
# fwd q256->512 and bwd (256,512)->(1024,1024) cut the step 472->438 ms
# (0.576->0.621 MFU).  Bigger q tiles amortize the per-block epilogue;
# the backward wants square-ish tiles since it streams both dQ and
# dK/dV.  _fit_block still shrinks these for short sequences.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
DEFAULT_BWD_BLOCK_Q = 1024
DEFAULT_BWD_BLOCK_K = 1024


def _fit_block(default: int, length: int) -> int:
    """Largest power-of-two tile ≤ default that divides ``length``."""
    block = min(default, length)
    while block > 128 and length % block:
        block //= 2
    return block


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
            scale: float, causal: bool, block_q: int, block_k: int,
            num_kv_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: KV blocks strictly above the diagonal contribute nothing.
    q_start = iq * block_q
    k_start = ik * block_k
    needed = (not causal) or (k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _attend():
        # Keep matmul inputs in the native (bf16) dtype — the MXU runs at
        # full rate with fp32 accumulation via preferred_element_type.
        q = q_ref[0, 0]                                      # (BQ, D)
        k = k_ref[0, 0]                                      # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (BQ, BK)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos > q_pos, NEG_INF, s)

        m_prev = m_ref[:]                                    # (BQ, 128)
        s_max = jnp.max(s, axis=-1, keepdims=True)           # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(s_max, m_prev.shape))
        p = jnp.exp(s - m_new[:, :1])                        # (BQ, BK)
        corr = jnp.exp(m_prev - m_new)                       # (BQ, 128)
        l_ref[:] = l_ref[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), corr.shape)
        v = v_ref[0, 0]                                      # (BK, D)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (BQ, D)
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv
        m_ref[:] = m_new

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # logsumexp residual for the backward: m + log(l) per row.
        # ((BQ, 1) trailing unit dim — TPU block layouts want the last
        # two dims tileable, which (1, BQ) is not.)
        lse_ref[0, 0] = m_ref[:, :1] + jnp.log(l)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"))
def flash_attention_fwd_lse(q, k, v, *, causal: bool = True,
                            scale: float | None = None,
                            block_q: int | None = None,
                            block_k: int | None = None,
                            interpret: bool | None = None):
    """q: (batch, q_len, heads, dim); k/v: (batch, kv_len, kv_heads, dim).
    Returns (out (B,S,H,D) in q.dtype, lse (B,H,S) fp32)."""
    batch, q_len, num_heads, head_dim = q.shape
    kv_len, num_kv_heads = k.shape[1], k.shape[2]
    groups = num_heads // num_kv_heads
    scale_val = scale if scale is not None else head_dim ** -0.5
    block_q = _fit_block(block_q or DEFAULT_BLOCK_Q, q_len)
    block_k = _fit_block(block_k or DEFAULT_BLOCK_K, kv_len)
    if q_len % block_q or kv_len % block_k:
        raise ValueError(
            f"sequence lengths ({q_len}, {kv_len}) must tile by "
            f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    qt = q.transpose(0, 2, 1, 3)                             # (B,H,S,D)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    num_q_blocks = q_len // block_q
    num_kv_blocks = kv_len // block_k
    grid = (batch, num_heads, num_q_blocks, num_kv_blocks)

    kernel = functools.partial(
        _kernel, scale=scale_val, causal=causal, block_q=block_q,
        block_k=block_k, num_kv_blocks=num_kv_blocks)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, num_heads, q_len, 1),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def flash_attention_forward(q, k, v, *, causal: bool = True,
                            scale: float | None = None,
                            block_q: int | None = None,
                            block_k: int | None = None,
                            interpret: bool | None = None):
    """Forward only — output without the lse residual."""
    out, _ = flash_attention_fwd_lse(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret)
    return out


# ------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale: float, causal: bool, block_q: int,
               block_k: int, num_kv_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = iq * block_q
    k_start = ik * block_k
    needed = (not causal) or (k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _accumulate():
        q = q_ref[0, 0]                                       # (BQ, D)
        k = k_ref[0, 0]                                       # (BK, D)
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # (BQ, BK)
        p = jnp.exp(s - lse_ref[0, 0])                        # lse (BQ, 1)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(k_pos > q_pos, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BQ, BK)
        ds = p * (dp - delta_ref[0, 0]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(q.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BQ, D)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def flash_attention_backward(q, k, v, out, lse, do, *, causal: bool,
                             scale: float | None = None,
                             block_q: int | None = None,
                             block_k: int | None = None,
                             interpret: bool | None = None):
    """Returns (dq, dk, dv) matching the input layouts
    (q: (B,S,H,D); k/v: (B,S,KVH,D))."""
    batch, q_len, num_heads, head_dim = q.shape
    kv_len, num_kv_heads = k.shape[1], k.shape[2]
    groups = num_heads // num_kv_heads
    scale_val = scale if scale is not None else head_dim ** -0.5
    block_q = _fit_block(block_q or DEFAULT_BWD_BLOCK_Q, q_len)
    block_k = _fit_block(block_k or DEFAULT_BWD_BLOCK_K, kv_len)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    qt = q.transpose(0, 2, 1, 3)                              # (B,H,S,D)
    kt = k.transpose(0, 2, 1, 3)                              # (B,KVH,S,D)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    # delta = rowsum(dO * O): one fused elementwise+reduce, fp32.
    # Trailing unit dim for TPU block tiling (same reason as lse).
    delta = jnp.sum(dot.astype(jnp.float32)
                    * out.transpose(0, 2, 1, 3).astype(jnp.float32),
                    axis=-1, keepdims=True)                   # (B,H,S,1)
    lse4 = lse[..., None]                                     # (B,H,S,1)

    num_q_blocks = q_len // block_q
    num_kv_blocks = kv_len // block_k

    # ---- dq sweep: grid (b, h, q_blocks, kv_blocks)
    dq_kernel = functools.partial(
        _dq_kernel, scale=scale_val, causal=causal, block_q=block_q,
        block_k=block_k, num_kv_blocks=num_kv_blocks)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(batch, num_heads, num_q_blocks, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, head_dim),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse4, delta)

    # ---- dk/dv sweep: grid (b, kv_heads, kv_blocks, groups·q_blocks);
    # each KV head accumulates over all its query heads' q blocks.
    num_inner = groups * num_q_blocks

    def _dkv(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
             dk_ref, dv_ref, dk_acc, dv_acc):
        ik = pl.program_id(2)
        inner = pl.program_id(3)
        iq = inner % num_q_blocks

        @pl.when(inner == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        q_start = iq * block_q
        k_start = ik * block_k
        needed = (not causal) or (q_start + block_q - 1 >= k_start)

        @pl.when(needed)
        def _accumulate():
            qb = q_ref[0, 0]                                  # (BQ, D)
            kb = k_ref[0, 0]                                  # (BK, D)
            vb = v_ref[0, 0]
            dob = do_ref[0, 0]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale_val
            p = jnp.exp(s - lse_ref[0, 0])                    # lse (BQ,1)
            if causal:
                q_pos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = k_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                p = jnp.where(k_pos > q_pos, 0.0, p)
            pb = p.astype(qb.dtype)
            dv_acc[:] += jax.lax.dot_general(
                pb, dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # (BK, D)
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)           # (BQ, BK)
            ds = (p * (dp - delta_ref[0, 0])
                  * scale_val).astype(qb.dtype)
            dk_acc[:] += jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # (BK, D)

        @pl.when(inner == num_inner - 1)
        def _finalize():
            dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    def _q_head(kvh, inner, g=groups):
        return kvh * g + inner // num_q_blocks

    dk, dv = pl.pallas_call(
        _dkv,
        grid=(batch, num_kv_heads, num_kv_blocks, num_inner),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, kvh, j, i: (b, _q_head(kvh, i),
                                               i % num_q_blocks, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, kvh, j, i: (b, kvh, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, kvh, j, i: (b, kvh, j, 0)),
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, kvh, j, i: (b, _q_head(kvh, i),
                                               i % num_q_blocks, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, kvh, j, i: (b, _q_head(kvh, i),
                                               i % num_q_blocks, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, kvh, j, i: (b, _q_head(kvh, i),
                                               i % num_q_blocks, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, kvh, j, i: (b, kvh, j, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, kvh, j, i: (b, kvh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kt.shape, k.dtype),
            jax.ShapeDtypeStruct(vt.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse4, delta)

    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))
