"""TPU compute ops: attention (blockwise / pallas flash / ring dispatch),
rotary embeddings, rmsnorm."""

from ant_ray_tpu.ops.attention import attention, blockwise_attention
from ant_ray_tpu.ops.rmsnorm import rmsnorm
from ant_ray_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "apply_rope",
    "attention",
    "blockwise_attention",
    "rmsnorm",
    "rope_frequencies",
]
