"""Attention implementations and the dispatch layer.

* :func:`blockwise_attention` — pure-JAX flash-style attention: a
  ``lax.scan`` over KV blocks with online softmax, O(seq · block) memory,
  fully differentiable (JAX derives the backward through the scan, and
  ``jax.checkpoint`` on the block body keeps the residuals bounded).  This
  is the training default: static shapes, MXU-shaped matmuls, no custom
  VJP to maintain.
* pallas flash forward kernel (ops/pallas/flash_attention.py) — the fast
  forward path, wired as custom_vjp with blockwise recompute backward.
* :func:`attention` — dispatcher: pallas on TPU when shapes tile cleanly,
  blockwise otherwise; ring attention (parallel/ring.py) takes over when
  the sequence axis is sharded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # avoids -inf NaN pitfalls in fully-masked blocks


def _repeat_kv(k, groups: int):
    return jnp.repeat(k, groups, axis=2) if groups > 1 else k


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_k"))
def blockwise_attention(q, k, v, *, causal: bool = True,
                        scale: float | None = None, block_k: int = 512):
    """Flash-style attention in pure JAX.

    q: (batch, q_len, heads, dim); k/v: (batch, kv_len, kv_heads, dim).
    Memory is O(q_len · block_k) per head instead of O(q_len · kv_len).
    """
    batch, q_len, num_heads, head_dim = q.shape
    kv_len, num_kv_heads = k.shape[1], k.shape[2]
    groups = num_heads // num_kv_heads
    scale = scale if scale is not None else head_dim ** -0.5
    block_k = min(block_k, kv_len)
    if kv_len % block_k != 0:
        raise ValueError(f"kv_len {kv_len} % block_k {block_k} != 0")
    num_blocks = kv_len // block_k

    # Matmul inputs stay in the model dtype (bf16 on TPU) with fp32
    # accumulation — fp32 inputs would cut the MXU rate severalfold.
    qt = q.transpose(0, 2, 1, 3)                                 # b h q d
    kt = _repeat_kv(k, groups).transpose(0, 2, 1, 3)
    vt = _repeat_kv(v, groups).transpose(0, 2, 1, 3)
    k_blocks = kt.reshape(batch, num_heads, num_blocks, block_k, head_dim)
    v_blocks = vt.reshape(batch, num_heads, num_blocks, block_k, head_dim)

    q_pos = jnp.arange(q_len)

    @jax.checkpoint
    def body(carry, blk):
        o, l, m = carry
        k_b, v_b, blk_idx = blk
        scores = jnp.einsum("bhqd,bhkd->bhqk", qt, k_b,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            kv_pos = blk_idx * block_k + jnp.arange(block_k)
            mask = kv_pos[None, :] > q_pos[:, None]
            scores = jnp.where(mask[None, None], NEG_INF, scores)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(qt.dtype), v_b,
            preferred_element_type=jnp.float32)
        l = l * corr + jnp.sum(p, axis=-1)
        return (o, l, m_new), None

    o0 = jnp.zeros((batch, num_heads, q_len, head_dim), jnp.float32)
    l0 = jnp.zeros((batch, num_heads, q_len), jnp.float32)
    m0 = jnp.full((batch, num_heads, q_len), NEG_INF, jnp.float32)
    (o, l, _m), _ = lax.scan(
        body, (o0, l0, m0),
        (k_blocks.transpose(2, 0, 1, 3, 4),
         v_blocks.transpose(2, 0, 1, 3, 4),
         jnp.arange(num_blocks)))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def _pallas_available() -> bool:
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001
        return False
    return backend in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    from ant_ray_tpu.ops.pallas.flash_attention import flash_attention_forward  # noqa: PLC0415

    return flash_attention_forward(q, k, v, causal=causal, scale=scale)


def _flash_fwd(q, k, v, causal, scale):
    from ant_ray_tpu.ops.pallas.flash_attention import flash_attention_fwd_lse  # noqa: PLC0415

    from jax.ad_checkpoint import checkpoint_name  # noqa: PLC0415

    out, lse = flash_attention_fwd_lse(q, k, v, causal=causal, scale=scale)
    # Named so remat policies can keep the attention output + softmax
    # stats without saving (or recomputing) anything inside the kernel:
    # saveable_attention_policy() below matches these names.
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, residuals, g):
    from ant_ray_tpu.ops.pallas.flash_attention import flash_attention_backward  # noqa: PLC0415

    q, k, v, out, lse = residuals
    return flash_attention_backward(q, k, v, out, lse, g, causal=causal,
                                    scale=scale)


_flash.defvjp(_flash_fwd, _flash_bwd)
_flash_with_blockwise_bwd = _flash  # back-compat alias


def saveable_attention_policy():
    """Remat policy: save matmul outputs AND the flash kernel's named
    residuals (attention output + logsumexp), so the backward pass never
    re-runs the attention forward.  Combine with jax.checkpoint."""
    cp = jax.checkpoint_policies
    return cp.save_from_both_policies(
        cp.dots_saveable,
        cp.save_only_these_names("attn_out", "attn_lse"))


def attention(q, k, v, *, causal: bool = True, scale: float | None = None,
              impl: str = "auto"):
    """Dispatch: 'pallas' | 'blockwise' | 'reference' | 'auto'."""
    if impl == "auto":
        seq_ok = q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
        dim_ok = q.shape[-1] in (64, 128, 256)
        impl = ("pallas" if _pallas_available() and seq_ok and dim_ok
                else "blockwise")
    if impl == "pallas":
        return _flash_with_blockwise_bwd(q, k, v, causal, scale)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, causal=causal, scale=scale)
    if impl == "reference":
        from ant_ray_tpu.parallel.ring import reference_attention  # noqa: PLC0415

        return reference_attention(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")
