"""Rotary position embeddings (Llama-style, half-split layout)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0,
                     dtype=jnp.float32):
    """Precompute cos/sin tables: (max_seq, head_dim // 2)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, positions=None):
    """x: (batch, seq, heads, head_dim); cos/sin: (max_seq, head_dim//2);
    positions: (batch, seq) int32 (defaults to arange)."""
    seq = x.shape[1]
    if positions is None:
        cos_sel = cos[:seq][None, :, None, :]     # (1, s, 1, d/2)
        sin_sel = sin[:seq][None, :, None, :]
    else:
        cos_sel = cos[positions][:, :, None, :]   # (b, s, 1, d/2)
        sin_sel = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos_sel - x2 * sin_sel, x2 * cos_sel + x1 * sin_sel], axis=-1)
    return out.astype(x.dtype)
