"""RMSNorm.  A plain jnp formulation — XLA fuses the reduction and scale
into neighboring ops on TPU, so a hand kernel buys nothing here; the hot
ops that do deserve Pallas live in ops/pallas/."""

from __future__ import annotations

import jax.numpy as jnp


def rmsnorm(x, weight, eps: float = 1e-5):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    scale = jnp.reciprocal(
        jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps))
    return (x32 * scale).astype(dtype) * weight
