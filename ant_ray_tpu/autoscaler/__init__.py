"""Autoscaler (ref capability: ray.autoscaler v2 — demand-driven node
provisioning over pluggable node providers)."""

from ant_ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ant_ray_tpu.autoscaler.node_provider import (
    GkeApiError,
    GkeRestNodePoolClient,
    GkeTpuNodePoolProvider,
    LocalSubprocessProvider,
    NodeProvider,
    NodeTypeConfig,
    tpu_slice_node_type,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "GkeApiError",
    "GkeRestNodePoolClient",
    "GkeTpuNodePoolProvider",
    "LocalSubprocessProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "tpu_slice_node_type",
]
