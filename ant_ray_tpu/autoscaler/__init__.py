"""Autoscaler (ref capability: ray.autoscaler v2 — demand-driven node
provisioning over pluggable node providers)."""

from ant_ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ant_ray_tpu.autoscaler.node_provider import (
    GkeTpuNodePoolProvider,
    LocalSubprocessProvider,
    NodeProvider,
    NodeTypeConfig,
    tpu_slice_node_type,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "GkeTpuNodePoolProvider",
    "LocalSubprocessProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "tpu_slice_node_type",
]
