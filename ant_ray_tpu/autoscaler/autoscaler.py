"""The autoscaler control loop.

Re-design of the reference's v2 autoscaler (ref:
python/ray/autoscaler/v2/autoscaler.py:50 — Reconciler over cluster
status + instance manager + scheduler) on this framework's primitives:

* **input**: the GCS's unfulfilled-demand table (``ResourceDemands`` —
  recorded on every SelectNode / actor-scheduling miss) plus the live
  node table (``GetAllNodes``);
* **decision**: first-fit bin-packing of demand shapes onto configured
  node types, bounded by per-type max_workers; min_workers backfill;
  idle-node termination after ``idle_timeout_s`` (only nodes this
  autoscaler launched — the head and statically-provisioned nodes are
  never touched);
* **actuation**: a NodeProvider (node_provider.py).

Run it in-process (``start()`` spawns the loop thread next to the
driver/head) or drive ``run_once()`` from a supervisor.  Heartbeats to
the GCS flip the cluster into "infeasible demands wait for capacity"
mode (core.py lease loop, gcs.py actor scheduling).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from ant_ray_tpu._private.protocol import ClientPool
from ant_ray_tpu.autoscaler.node_provider import (
    NodeProvider,
    NodeTypeConfig,
)

logger = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    node_types: list[NodeTypeConfig] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    interval_s: float = 5.0
    # Max nodes launched per reconcile round (upscaling_speed analogue).
    max_launches_per_round: int = 8


def _fits(demand: dict, node_type: NodeTypeConfig,
          selector: dict | None = None) -> bool:
    if selector:
        labels = {**node_type.labels, "art/node-type": node_type.name,
                  "art/autoscaled": "1"}
        if not all(labels.get(k) == v for k, v in selector.items()):
            return False
    return all(node_type.resources.get(k, 0.0) >= v
               for k, v in demand.items())


class Autoscaler:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 config: AutoscalerConfig):
        self._gcs_address = gcs_address
        self._provider = provider
        self._config = config
        self._clients = ClientPool()
        self._launched: dict[str, str] = {}      # provider id -> type
        self._idle_since: dict[str, float] = {}  # provider id -> ts
        self._no_address_warned: set[str] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="art-autoscaler")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self._config.interval_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("autoscaler reconcile failed")

    # --------------------------------------------------------- one round

    def run_once(self) -> dict:
        """One reconcile: returns {"launched": [...], "terminated": [...]}
        for observability/tests."""
        gcs = self._clients.get(self._gcs_address)
        gcs.call("AutoscalerHeartbeat", {}, retries=3)
        demands = gcs.call("ResourceDemands", {}, retries=3) or []
        nodes = list((gcs.call("GetAllNodes", {}, retries=3)
                      or {}).values())

        launched = self._scale_up(demands, nodes)
        budget = self._config.max_launches_per_round - len(launched)
        launched += self._backfill_min_workers(budget)
        terminated = self._scale_down(nodes)
        return {"launched": launched, "terminated": terminated}

    # --------------------------------------------------------- scale up

    def _counts_by_type(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for type_name in self._provider.non_terminated_nodes().values():
            counts[type_name] = counts.get(type_name, 0) + 1
        return counts

    @staticmethod
    def _node_satisfies(info, shape: dict, selector: dict | None) -> bool:
        """Can this live node EVER run the shape?  (total capacity +
        labels — mirrors the GCS's own infeasibility test, so a demand
        recorded before a node arrived stops driving launches once the
        node registers.)"""
        if not getattr(info, "alive", False):
            return False
        labels = getattr(info, "labels", {}) or {}
        if selector and not all(labels.get(k) == v
                                for k, v in selector.items()):
            return False
        total = getattr(info, "total_resources", {}) or {}
        return all(total.get(k, 0.0) >= v for k, v in shape.items())

    def _scale_up(self, demands: list[dict], nodes: list) -> list[str]:
        counts = self._counts_by_type()
        launched: list[str] = []
        budget = self._config.max_launches_per_round
        for demand in demands:
            if budget <= 0:
                break
            shape = demand.get("resources", {})
            selector = demand.get("label_selector") or None
            # Stale demand: some live node can already run it (leases
            # queue there); launching more would double-provision.
            if any(self._node_satisfies(n, shape, selector)
                   for n in nodes):
                continue
            # Skip shapes a pending node will satisfy — launched this
            # round, or launched earlier and still registering with the
            # GCS (provider sees it, the node table doesn't yet).
            pending_types = launched + list(
                self._provider.non_terminated_nodes().values())
            if any(_fits(shape, self._type_by_name(t), selector)
                   for t in pending_types):
                continue
            choice = self._pick_type(shape, selector, counts)
            if choice is None:
                logger.warning(
                    "demand %s (selector %s) fits no configured node "
                    "type within max_workers", shape, selector)
                continue
            pid = self._provider.create_node(choice)
            self._launched[pid] = choice.name
            counts[choice.name] = counts.get(choice.name, 0) + 1
            launched.append(choice.name)
            budget -= 1
            logger.info("autoscaler launched %s (%s) for demand %s",
                        pid, choice.name, shape)
        return launched

    def _backfill_min_workers(self, budget: int) -> list[str]:
        counts = self._counts_by_type()
        launched = []
        for node_type in self._config.node_types:
            while counts.get(node_type.name, 0) < node_type.min_workers:
                if budget <= 0:  # rest next round — keep rounds short
                    return launched
                pid = self._provider.create_node(node_type)
                self._launched[pid] = node_type.name
                counts[node_type.name] = counts.get(node_type.name, 0) + 1
                launched.append(node_type.name)
                budget -= 1
        return launched

    def _type_by_name(self, name: str) -> NodeTypeConfig:
        for node_type in self._config.node_types:
            if node_type.name == name:
                return node_type
        raise KeyError(name)

    def _pick_type(self, shape: dict, selector: dict | None,
                   counts: dict[str, int]) -> NodeTypeConfig | None:
        """Smallest feasible type with headroom (first fit by total
        resource sum — the v2 scheduler's utilization-score analogue)."""
        feasible = [t for t in self._config.node_types
                    if _fits(shape, t, selector)
                    and counts.get(t.name, 0) < t.max_workers]
        if not feasible:
            return None
        return min(feasible, key=lambda t: sum(t.resources.values()))

    # --------------------------------------------------------- scale down

    def _scale_down(self, nodes: list) -> list[str]:
        """Terminate autoscaled nodes idle past the timeout (never below
        min_workers for their type)."""
        provider_nodes = self._provider.non_terminated_nodes()
        counts = self._counts_by_type()
        now = time.monotonic()
        terminated: list[str] = []

        # Which GCS nodes are idle?  (all resources back to total and no
        # leases — the heartbeat view.)
        idle_addresses = set()
        for info in nodes:
            if not getattr(info, "alive", False):
                continue
            total = getattr(info, "total_resources", {})
            available = getattr(info, "available_resources", {})
            if all(available.get(k, 0.0) >= v for k, v in total.items()):
                idle_addresses.add(getattr(info, "address", ""))

        for pid, type_name in list(provider_nodes.items()):
            if pid not in self._launched:
                continue  # not ours (statically provisioned)
            address = self._provider.node_address(pid)
            if address is None:
                if pid not in self._no_address_warned:
                    self._no_address_warned.add(pid)
                    logger.warning(
                        "provider gives no address for %s — idle "
                        "scale-down disabled for it; terminate via the "
                        "provider explicitly when it drains", pid)
                continue
            if address not in idle_addresses:
                self._idle_since.pop(pid, None)
                continue
            node_type = self._type_by_name(type_name)
            if counts.get(type_name, 0) <= node_type.min_workers:
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            if now - first_idle < self._config.idle_timeout_s:
                continue
            logger.info("autoscaler terminating idle node %s (%s)",
                        pid, type_name)
            self._provider.terminate_node(pid)
            self._launched.pop(pid, None)
            self._idle_since.pop(pid, None)
            counts[type_name] -= 1
            terminated.append(type_name)
        return terminated
