"""The autoscaler control loop.

Re-design of the reference's v2 autoscaler (ref:
python/ray/autoscaler/v2/autoscaler.py:50 — Reconciler over cluster
status + instance manager + scheduler) on this framework's primitives:

* **input**: the GCS's unfulfilled-demand table (``ResourceDemands`` —
  recorded on every SelectNode / actor-scheduling miss) plus the live
  node table (``GetAllNodes``);
* **decision**: first-fit bin-packing of demand shapes onto configured
  node types, bounded by per-type max_workers; min_workers backfill;
  idle-node termination after ``idle_timeout_s`` (only nodes this
  autoscaler launched — the head and statically-provisioned nodes are
  never touched);
* **actuation**: a NodeProvider (node_provider.py).

Run it in-process (``start()`` spawns the loop thread next to the
driver/head) or drive ``run_once()`` from a supervisor.  Heartbeats to
the GCS flip the cluster into "infeasible demands wait for capacity"
mode (core.py lease loop, gcs.py actor scheduling).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field

from ant_ray_tpu._private.protocol import ClientPool
from ant_ray_tpu.autoscaler.node_provider import (
    NodeProvider,
    NodeTypeConfig,
)

logger = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    node_types: list[NodeTypeConfig] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    interval_s: float = 5.0
    # Max launch units per reconcile round (upscaling_speed analogue).
    max_launches_per_round: int = 8
    # After launching for a gang demand, wait this long for the hosts to
    # register before considering launching for the same gang again
    # (a GKE node-pool resize takes minutes; relaunching every reconcile
    # would provision N slices for one demand).
    gang_provision_grace_s: float = 120.0


# ---------------------------------------------------------------- gang plan
# Capacity-feasibility planner for gang (placement group) demands: can
# this host set EVER hold every bundle under the strategy + selector +
# same-label constraints?  Mirrors the GCS's _plan_bundles semantics
# (gcs.py) but runs on TOTAL resources — the autoscaler asks "is more
# hardware needed", not "does it fit right now" (ref: gang resource
# requests in src/ray/gcs/gcs_autoscaler_state_manager.h consumed by
# python/ray/autoscaler/v2/scheduler.py).


def _plan_gang_in(hosts: list[dict], bundles, selectors,
                  strategy) -> tuple[list[str] | None, int]:
    """Greedy assignment of bundles to ``hosts`` ([{"id", "labels",
    "resources"}]).  Returns (plan, -1) or (None, first_failed_bundle)."""
    remaining = {h["id"]: dict(h["resources"]) for h in hosts}
    labels = {h["id"]: h["labels"] for h in hosts}

    def sel_ok(hid, index):
        if not selectors or index >= len(selectors):
            return True
        return all(labels[hid].get(k) == v
                   for k, v in (selectors[index] or {}).items())

    def fits(hid, bundle):
        return all(remaining[hid].get(k, 0.0) >= v
                   for k, v in bundle.items())

    def take(hid, bundle):
        for k, v in bundle.items():
            remaining[hid][k] = remaining[hid].get(k, 0.0) - v

    if strategy in ("STRICT_PACK", "PACK"):
        for h in hosts:
            hid = h["id"]
            if not all(sel_ok(hid, i) for i in range(len(bundles))):
                continue
            snapshot = dict(remaining[hid])
            ok = True
            for bundle in bundles:
                if fits(hid, bundle):
                    take(hid, bundle)
                else:
                    ok = False
                    break
            remaining[hid] = snapshot
            if ok:
                return [hid] * len(bundles), -1
        if strategy == "STRICT_PACK":
            return None, 0
    used: set = set()
    plan: list[str] = []
    for index, bundle in enumerate(bundles):
        chosen = None
        for h in sorted(hosts, key=lambda h: (h["id"] in used, h["id"])):
            hid = h["id"]
            if strategy == "STRICT_SPREAD" and hid in used:
                continue
            if sel_ok(hid, index) and fits(hid, bundle):
                chosen = hid
                break
        if chosen is None:
            return None, index
        take(chosen, bundle)
        used.add(chosen)
        plan.append(chosen)
    return plan, -1


def plan_gang(hosts: list[dict], bundles, selectors, strategy,
              same_label) -> list[str] | None:
    """Full gang plan: with ``same_label``, every chosen host must share
    one value of that label key (the slice-affinity constraint)."""
    if same_label is not None:
        values = sorted({h["labels"].get(same_label) for h in hosts
                         if h["labels"].get(same_label) is not None})
        for value in values:
            group = [h for h in hosts
                     if h["labels"].get(same_label) == value]
            plan, _ = _plan_gang_in(group, bundles, selectors, strategy)
            if plan is not None:
                return plan
        return None
    plan, _ = _plan_gang_in(hosts, bundles, selectors, strategy)
    return plan


def _fits(demand: dict, node_type: NodeTypeConfig,
          selector: dict | None = None) -> bool:
    if selector:
        labels = {**node_type.labels, "art/node-type": node_type.name,
                  "art/autoscaled": "1"}
        if not all(labels.get(k) == v for k, v in selector.items()):
            return False
    return all(node_type.resources.get(k, 0.0) >= v
               for k, v in demand.items())


class Autoscaler:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 config: AutoscalerConfig):
        self._gcs_address = gcs_address
        self._provider = provider
        self._config = config
        self._clients = ClientPool()
        self._launched: dict[str, str] = {}      # provider id -> type
        self._idle_since: dict[str, float] = {}  # provider id -> ts
        # gang demand key -> launch time: suppresses relaunching while
        # the provisioned hosts are still registering.
        self._gang_pending: dict[str, float] = {}
        self._no_address_warned: set[str] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="art-autoscaler")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self._config.interval_s):
            try:
                self.run_once()
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("autoscaler reconcile failed")

    # --------------------------------------------------------- one round

    def run_once(self) -> dict:
        """One reconcile: returns {"launched": [...], "terminated": [...]}
        for observability/tests."""
        gcs = self._clients.get(self._gcs_address)
        gcs.call("AutoscalerHeartbeat", {}, retries=3)
        demands = gcs.call("ResourceDemands", {}, retries=3) or []
        nodes = list((gcs.call("GetAllNodes", {}, retries=3)
                      or {}).values())

        launched = self._scale_up(demands, nodes)
        budget = self._config.max_launches_per_round - len(launched)
        launched += self._backfill_min_workers(budget)
        terminated = self._scale_down(nodes)
        return {"launched": launched, "terminated": terminated}

    # --------------------------------------------------------- scale up

    def _counts_by_type(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for type_name in self._provider.non_terminated_nodes().values():
            counts[type_name] = counts.get(type_name, 0) + 1
        return counts

    @staticmethod
    def _node_satisfies(info, shape: dict, selector: dict | None) -> bool:
        """Can this live node EVER run the shape?  (total capacity +
        labels — mirrors the GCS's own infeasibility test, so a demand
        recorded before a node arrived stops driving launches once the
        node registers.)"""
        if not getattr(info, "alive", False):
            return False
        labels = getattr(info, "labels", {}) or {}
        if selector and not all(labels.get(k) == v
                                for k, v in selector.items()):
            return False
        total = getattr(info, "total_resources", {}) or {}
        return all(total.get(k, 0.0) >= v for k, v in shape.items())

    @staticmethod
    def _node_views(nodes: list, field: str = "total_resources"
                    ) -> list[dict]:
        """Live GCS nodes as planner host views."""
        return [{"id": getattr(n, "node_id", getattr(n, "address", "")),
                 "labels": getattr(n, "labels", {}) or {},
                 "resources": getattr(n, field, {}) or {}}
                for n in nodes if getattr(n, "alive", False)]

    def _scale_up(self, demands: list[dict], nodes: list) -> list[str]:
        counts = self._counts_by_type()
        launched: list[str] = []
        budget = self._config.max_launches_per_round
        now = time.monotonic()
        gang_keys_seen: set[str] = set()
        for demand in demands:
            if budget <= 0:
                break
            if "bundles" in demand:
                gang_keys_seen.add(self._gang_key(demand))
                units = self._scale_up_gang(demand, nodes, counts,
                                            budget, now, launched)
                budget -= units
                continue
            shape = demand.get("resources", {})
            if not shape:
                # An empty shape would "fit" anywhere and "be satisfied"
                # by any node — never act on one (malformed demand).
                continue
            selector = demand.get("label_selector") or None
            # Stale demand: some live node can already run it (leases
            # queue there); launching more would double-provision.
            if any(self._node_satisfies(n, shape, selector)
                   for n in nodes):
                continue
            # Skip shapes a pending node will satisfy — launched this
            # round, or launched earlier and still registering with the
            # GCS (provider sees it, the node table doesn't yet).
            pending_types = launched + list(
                self._provider.non_terminated_nodes().values())
            if any(_fits(shape, self._type_by_name(t), selector)
                   for t in pending_types):
                continue
            choice = self._pick_type(shape, selector, counts)
            if choice is None:
                logger.warning(
                    "demand %s (selector %s) fits no configured node "
                    "type within max_workers", shape, selector)
                continue
            pid = self._provider.create_node(choice)
            self._launched[pid] = choice.name
            counts[choice.name] = counts.get(choice.name, 0) + 1
            launched.append(choice.name)
            budget -= 1
            logger.info("autoscaler launched %s (%s) for demand %s",
                        pid, choice.name, shape)
        # Gangs that vanished (PG committed or removed) free their
        # provisioning-grace records.
        for key in [k for k in self._gang_pending
                    if k not in gang_keys_seen]:
            del self._gang_pending[key]
        return launched

    # ----------------------------------------------------- gang scale up

    @staticmethod
    def _gang_key(demand: dict) -> str:
        # Per-PG when the GCS says which PG this is (two identical-shape
        # pending PGs are two gangs needing two node sets).
        if demand.get("pg_id"):
            return f"pg:{demand['pg_id']}"
        return json.dumps(
            [[sorted(b.items()) for b in demand["bundles"]],
             [sorted((s or {}).items())
              for s in demand.get("bundle_selectors") or []],
             demand.get("strategy"), demand.get("same_label")])

    def _scale_up_gang(self, demand: dict, nodes: list,
                       counts: dict[str, int], budget: int, now: float,
                       launched: list[str]) -> int:
        """Provision for one gang demand (an unplaceable placement
        group): pick a node SET that satisfies every bundle atomically
        — for slice PGs (same_label), one whole gang-unit launch; for
        plain gangs, the minimal set of single launches — and launch it
        as a unit.  Returns the number of launch units consumed.

        Ref: python/ray/autoscaler/v2/scheduler.py gang resource
        requests; src/ray/gcs/gcs_autoscaler_state_manager.h."""
        bundles = demand["bundles"]
        selectors = demand.get("bundle_selectors")
        strategy = demand.get("strategy", "PACK")
        same_label = demand.get("same_label")
        key = self._gang_key(demand)

        # AVAILABLE resources, not totals: a gang is per-PG, so capacity
        # another committed PG or running job holds cannot serve it —
        # a pending gang whose resources are merely occupied still needs
        # new hardware (ref: v2 scheduler treats pending gang requests
        # as demand against free capacity).
        views = self._node_views(nodes, "available_resources")
        if plan_gang(views, bundles, selectors, strategy,
                     same_label) is not None:
            # Some live node set can hold the whole gang — placement is
            # the GCS PG scheduler's job, not ours.
            self._gang_pending.pop(key, None)
            return 0
        pending_since = self._gang_pending.get(key)
        if pending_since is not None and \
                now - pending_since < self._config.gang_provision_grace_s:
            return 0          # our earlier launch is still registering

        # 1) Whole-gang unit launch (TPU slice node types): one launch
        #    yields hosts_per_launch hosts that cover every bundle.
        unit_types = sorted(
            self._config.node_types,
            key=lambda t: sum(t.resources.values()) * t.hosts_per_launch)
        for node_type in unit_types:
            if counts.get(node_type.name, 0) >= node_type.max_workers:
                continue
            if plan_gang(node_type.launch_host_views(), bundles,
                         selectors, strategy, same_label) is None:
                continue
            if budget < 1:
                return 0
            pid = self._provider.create_node(node_type)
            self._launched[pid] = node_type.name
            counts[node_type.name] = counts.get(node_type.name, 0) + 1
            launched.append(node_type.name)
            self._gang_pending[key] = now
            logger.info(
                "autoscaler launched gang unit %s (%s, %d hosts) for "
                "%d-bundle gang demand", pid, node_type.name,
                node_type.hosts_per_launch, len(bundles))
            return 1

        if same_label is not None:
            # A slice-affinity gang can't be assembled from independent
            # single launches (each would carry a different slice id).
            logger.warning(
                "gang demand (%d bundles, same_label=%s) fits no "
                "configured gang-unit node type within max_workers — "
                "configure a node type with hosts_per_launch/"
                "launch_shared_label matching the slice "
                "(see tpu_slice_node_type)", len(bundles), same_label)
            return 0

        # 2) Plain gang: grow a virtual view of (live nodes + planned
        #    launches) until the whole gang plans, then launch the
        #    additions together — all or nothing within this round.
        needed: list[NodeTypeConfig] = []
        planned_counts = dict(counts)
        virtual = list(views)
        for _ in range(len(bundles)):
            plan, failed = _plan_gang_in(virtual, bundles, selectors,
                                         strategy)
            if plan is not None:
                break
            selector = (selectors[failed]
                        if selectors and failed < len(selectors) else None)
            choice = self._pick_type(bundles[failed], selector or None,
                                     planned_counts)
            if choice is None:
                logger.warning(
                    "gang demand bundle %s (selector %s) fits no "
                    "configured node type within max_workers",
                    bundles[failed], selector)
                return 0
            needed.append(choice)
            planned_counts[choice.name] = \
                planned_counts.get(choice.name, 0) + 1
            virtual += [{**h, "id": f"planned-{len(needed)}/{h['id']}"}
                        for h in choice.launch_host_views()]
        else:
            plan, _ = _plan_gang_in(virtual, bundles, selectors, strategy)
            if plan is None:
                return 0
        if not needed:
            return 0
        if len(needed) > budget:
            # A gang larger than one round's budget launches in chunks:
            # after the grace period the registered chunk shrinks the
            # replan, so successive rounds converge on the full set.
            logger.info(
                "gang needs %d launches but round budget leaves %d — "
                "launching a chunk, remainder next round",
                len(needed), budget)
            needed = needed[:budget]
        for node_type in needed:
            pid = self._provider.create_node(node_type)
            self._launched[pid] = node_type.name
            counts[node_type.name] = counts.get(node_type.name, 0) + 1
            launched.append(node_type.name)
        self._gang_pending[key] = now
        logger.info("autoscaler launched %d nodes (%s) for %d-bundle "
                    "gang demand", len(needed),
                    [t.name for t in needed], len(bundles))
        return len(needed)

    def _backfill_min_workers(self, budget: int) -> list[str]:
        counts = self._counts_by_type()
        launched = []
        for node_type in self._config.node_types:
            while counts.get(node_type.name, 0) < node_type.min_workers:
                if budget <= 0:  # rest next round — keep rounds short
                    return launched
                pid = self._provider.create_node(node_type)
                self._launched[pid] = node_type.name
                counts[node_type.name] = counts.get(node_type.name, 0) + 1
                launched.append(node_type.name)
                budget -= 1
        return launched

    def _type_by_name(self, name: str) -> NodeTypeConfig:
        for node_type in self._config.node_types:
            if node_type.name == name:
                return node_type
        raise KeyError(name)

    def _pick_type(self, shape: dict, selector: dict | None,
                   counts: dict[str, int]) -> NodeTypeConfig | None:
        """Smallest feasible type with headroom (first fit by total
        resource sum — the v2 scheduler's utilization-score analogue)."""
        feasible = [t for t in self._config.node_types
                    if _fits(shape, t, selector)
                    and counts.get(t.name, 0) < t.max_workers]
        if not feasible:
            return None
        return min(feasible, key=lambda t: sum(t.resources.values()))

    # --------------------------------------------------------- scale down

    def _scale_down(self, nodes: list) -> list[str]:
        """Terminate autoscaled nodes idle past the timeout (never below
        min_workers for their type)."""
        provider_nodes = self._provider.non_terminated_nodes()
        counts = self._counts_by_type()
        now = time.monotonic()
        terminated: list[str] = []

        # Which GCS nodes are idle?  (all resources back to total and no
        # leases — the heartbeat view.)
        idle_addresses = set()
        for info in nodes:
            if not getattr(info, "alive", False):
                continue
            total = getattr(info, "total_resources", {})
            available = getattr(info, "available_resources", {})
            if all(available.get(k, 0.0) >= v for k, v in total.items()):
                idle_addresses.add(getattr(info, "address", ""))

        for pid, type_name in list(provider_nodes.items()):
            if pid not in self._launched:
                continue  # not ours (statically provisioned)
            addresses = self._provider.node_addresses(pid)
            if addresses is None:
                if pid not in self._no_address_warned:
                    self._no_address_warned.add(pid)
                    logger.warning(
                        "provider gives no address for %s — idle "
                        "scale-down disabled for it; terminate via the "
                        "provider explicitly when it drains", pid)
                continue
            # A gang unit (TPU slice) terminates as a whole, so it only
            # counts as idle when EVERY host is idle.
            if not all(a in idle_addresses for a in addresses):
                self._idle_since.pop(pid, None)
                continue
            node_type = self._type_by_name(type_name)
            if counts.get(type_name, 0) <= node_type.min_workers:
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            if now - first_idle < self._config.idle_timeout_s:
                continue
            logger.info("autoscaler terminating idle node %s (%s)",
                        pid, type_name)
            self._provider.terminate_node(pid)
            self._launched.pop(pid, None)
            self._idle_since.pop(pid, None)
            counts[type_name] -= 1
            terminated.append(type_name)
        return terminated
